"""Static buffer-liveness / peak-HBM certifier over the :mod:`hlo_ir` IR.

Every other certifier in this repo bounds a *rate* (collective bytes,
host round-trips, lock orders); this one bounds the resource that
decides whether a program runs at all: device memory.  For each
computation it builds def/last-use intervals per instruction result,
threads aliasing through the ops that create views rather than buffers
(``tuple`` / ``get-tuple-element`` / ``bitcast`` / the
optimization-barrier chains the strategies emit), and sweeps a
peak-live-bytes bound:

- **Entry parameters** are argument buffers held by the caller for the
  whole dispatch: live ``[0, end]``, donated or not.
- **Constants** are baked into the executable: live from their def to
  the end (never freed).
- **`while` loops run steady-state**: the result ALIASES the carry
  operand (the in-place update buffer donation buys), and the body's
  transient peak is added ONCE — loop iterations reuse their buffers,
  so trip counts multiply FLOPs (:mod:`costmodel`) but never memory.
  The body is charged WITH its root (the freshly produced carry):
  XLA's loop double-buffering means old and new carry coexist at the
  instant the body finishes, donation or not.
- **Donation is proven in bytes, not leaf counts**: a ``while`` whose
  carry includes NON-donated entry parameters must copy them before
  overwriting (XLA copy-insertion) — the analyzer charges that copy
  (``undonated_copy_bytes``), so the donated and un-donated lowerings
  of the same window differ by exactly the carried state bytes.
- **Callees** (fusions, reducers, branches) contribute a transient
  spike at the call site: their internal peak with parameters and root
  excluded (operands and result are charged by the caller).

The bound is over whichever print the caller hands in; the audit feeds
it the PRE-optimization lowering, where entry shapes are still GLOBAL
(pre-SPMD) — so for shard_map programs the bound is per-*program*, an
upper bound on any single chip's share.  Validation is two-sided
(tests/test_memlife.py): never under ``compiled.memory_analysis()``'s
temp+output bytes on any zoo program, within :data:`COMPILED_BAND` of
it on the windowed train paths, and never under the runtime
``live_arrays`` gauge ``train/loop.emit_memory_gauges`` records.

The per-chip budget it certifies against is the single-sourced
:data:`costmodel.V5E_HBM_CAPACITY_BYTES`; :func:`check_memory` is the
jax-free repo self-check ``tools/lint_graft.py`` runs path-less (the
literals stay single-sourced, the committed fixtures keep proving the
donation delta).
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Tuple

from . import costmodel, hlo_ir, stats
from .pylint_rules import LintFinding

#: Static peak must sit within this factor of the compiled
#: ``memory_analysis()`` total (argument+output+temp) on the windowed
#: train paths — the declared tolerance band.  The static model is
#: deliberately conservative (nothing fuses, callee spikes sum, entry
#: shapes are pre-SPMD global), so the band is an over-approximation
#: ceiling, never an under-count licence; measured ratios on the CPU
#: backend sit at 1.1-2.0x.
COMPILED_BAND = 4.0

#: Ops whose result is a VIEW of operand storage — no new buffer.
_ALIAS_OPS = frozenset((
    "tuple", "get-tuple-element", "bitcast",
    "optimization-barrier", "opt-barrier", "after-all",
))

#: How many of the fattest program points a MemReport keeps.
TOP_SETS = 5
_TOP_MEMBERS = 8


@dataclass
class MemReport:
    """Static memory certificate for one program."""

    name: str
    peak_bytes: int = 0
    param_bytes: int = 0              # entry argument buffers (all live)
    donated_bytes: int = 0            # donated subset (in-place carry)
    carry_bytes: int = 0              # fattest while-carry in the entry
    undonated_copy_bytes: int = 0     # copy-insertion cost of missed donation
    constant_bytes: int = 0           # baked into the executable
    transient_peak_bytes: int = 0     # peak beyond the argument buffers
    output_bytes: int = 0             # root result (donated part aliases)
    # Top fattest live sets: {"position", "instruction", "live_bytes",
    # "members": [[buffer, bytes], ...]} — the "what do I shrink" view.
    top_sets: List[Dict] = field(default_factory=list)
    notes: List[str] = field(default_factory=list)

    @property
    def peak_mib(self) -> float:
        return self.peak_bytes / 2**20

    def to_dict(self) -> Dict:
        return {
            "name": self.name,
            "peak_mib": round(self.peak_bytes / 2**20, 3),
            "param_mib": round(self.param_bytes / 2**20, 3),
            "donated_mib": round(self.donated_bytes / 2**20, 3),
            "carry_mib": round(self.carry_bytes / 2**20, 3),
            "undonated_copy_mib": round(
                self.undonated_copy_bytes / 2**20, 3),
            "constant_mib": round(self.constant_bytes / 2**20, 3),
            "transient_peak_mib": round(
                self.transient_peak_bytes / 2**20, 3),
            "output_mib": round(self.output_bytes / 2**20, 3),
            "top_sets": [
                {**t, "live_mib": round(t["live_bytes"] / 2**20, 3),
                 "members": [[n, round(b / 2**20, 3)]
                             for n, b in t["members"]]}
                for t in self.top_sets],
            "notes": list(self.notes),
        }


def _donated_indices(module: hlo_ir.Module) -> FrozenSet[int]:
    idxs = set()
    for key in ("buffer_donor", "input_output_alias"):
        raw = module.attr(key)
        if raw:
            idxs |= {int(i) for i in re.findall(r"\(\s*(\d+)\s*,", raw)}
    return frozenset(idxs)


class _Analyzer:
    """One pass over a module; memoizes callee transient peaks."""

    def __init__(self, module: hlo_ir.Module):
        self.module = module
        self._transient_memo: Dict[Tuple[str, bool], int] = {}

    # -- callee transient peaks -------------------------------------------

    def transient_peak(self, cname: str, *, charge_root: bool,
                       stack: Tuple[str, ...] = ()) -> int:
        """Peak live bytes INSIDE computation ``cname`` beyond what its
        caller already charges: parameters excluded always, the root
        excluded unless ``charge_root`` (while bodies charge it — the
        fresh carry coexists with the old one)."""
        key = (cname, charge_root)
        if key in self._transient_memo:
            return self._transient_memo[key]
        if cname in stack or cname not in self.module.computations:
            return 0
        peak = self._sweep(self.module.computations[cname],
                           entry_mode=False, charge_root=charge_root,
                           stack=stack + (cname,))[0]
        self._transient_memo[key] = peak
        return peak

    # -- the liveness sweep -----------------------------------------------

    def _sweep(self, comp: hlo_ir.Computation, *, entry_mode: bool,
               charge_root: bool, stack: Tuple[str, ...],
               donated: FrozenSet[int] = frozenset(),
               report: Optional[MemReport] = None):
        """Event-sweep one computation.  Returns (peak_bytes, live_curve,
        buffers, defpos, lastuse) and, in entry mode, fills ``report``."""
        instrs = list(comp.instructions.values())
        n = len(instrs)
        if n == 0:
            return 0, [], {}, {}, {}

        origins: Dict[str, FrozenSet[str]] = {}
        buffers: Dict[str, int] = {}      # buffer -> bytes
        defpos: Dict[str, int] = {}
        lastuse: Dict[str, int] = {}
        spike: Dict[int, int] = {}        # position -> callee transient
        param_buffers: Dict[str, int] = {}   # buffer -> param index
        root_name = comp.root.name if comp.root is not None else None

        def alloc(buf: str, nbytes: int, pos: int) -> None:
            buffers[buf] = nbytes
            defpos[buf] = pos
            lastuse[buf] = pos

        for pos, ins in enumerate(instrs):
            op = ins.opcode
            if op == "parameter":
                if entry_mode:
                    alloc(ins.name, hlo_ir.result_bytes(ins), 0)
                    lastuse[ins.name] = n - 1   # caller-held argument
                    try:
                        param_buffers[ins.name] = int(ins.operand_raw[0])
                    except (IndexError, ValueError):
                        param_buffers[ins.name] = -1
                    origins[ins.name] = frozenset((ins.name,))
                else:
                    origins[ins.name] = frozenset()   # caller-owned
                continue

            used: set = set()
            for ref in ins.operands:
                used |= origins.get(ref, frozenset())
            for buf in used:
                lastuse[buf] = pos

            if op == "constant":
                alloc(ins.name, hlo_ir.result_bytes(ins), pos)
                lastuse[ins.name] = n - 1       # executable image, not freed
                origins[ins.name] = frozenset((ins.name,))
                continue
            if op in _ALIAS_OPS:
                origins[ins.name] = frozenset(used)
                continue

            if op == "while":
                body = costmodel._called_comp(ins, "body")
                cond = costmodel._called_comp(ins, "condition")
                extra = 0
                if body:
                    extra += self.transient_peak(body, charge_root=True,
                                                 stack=stack)
                if cond:
                    extra += self.transient_peak(cond, charge_root=False,
                                                 stack=stack)
                spike[pos] = spike.get(pos, 0) + extra
                carry = frozenset(used)
                if report is not None:
                    report.carry_bytes = max(
                        report.carry_bytes,
                        sum(buffers.get(b, 0) for b in carry))
                if entry_mode:
                    undonated = frozenset(
                        b for b in carry
                        if b in param_buffers
                        and param_buffers[b] not in donated)
                    copy_bytes = sum(buffers[b] for b in undonated)
                    if copy_bytes:
                        cbuf = ins.name + ":carry-copy"
                        alloc(cbuf, copy_bytes, pos)
                        carry = (carry - undonated) | {cbuf}
                        if report is not None:
                            report.undonated_copy_bytes += copy_bytes
                            report.notes.append(
                                f"while {ins.name}: {copy_bytes} carry "
                                f"bytes enter through non-donated entry "
                                f"parameters — copy-insertion charges a "
                                f"fresh buffer (donate them to erase it)")
                origins[ins.name] = carry
                continue

            # Generic allocating op (fusions, calls, reduces, branches,
            # custom-calls, copies, dots, ...): callee internals spike
            # at the call site, the result is a fresh buffer.
            for callee in ins.called:
                spike[pos] = spike.get(pos, 0) + self.transient_peak(
                    callee, charge_root=False, stack=stack)
            alloc(ins.name, hlo_ir.result_bytes(ins), pos)
            origins[ins.name] = frozenset((ins.name,))

        # Root results are live at the end (the caller fetches them).
        if root_name is not None:
            root_origins = origins.get(root_name, frozenset())
            for buf in root_origins:
                lastuse[buf] = n - 1
            if not charge_root:
                # Callee mode: the caller charges the result bytes.
                for buf in root_origins:
                    if buf in buffers and buf not in param_buffers:
                        buffers[buf] = 0

        # Event sweep: +bytes at def, -bytes after last use, plus the
        # per-position callee spike.
        delta = [0] * (n + 1)
        for buf, nbytes in buffers.items():
            delta[defpos[buf]] += nbytes
            delta[lastuse[buf] + 1] -= nbytes
        live = []
        running = 0
        for pos in range(n):
            running += delta[pos]
            live.append(running + spike.get(pos, 0))
        peak = max(live) if live else 0

        if report is not None:
            report.param_bytes = sum(
                buffers[b] for b in param_buffers)
            report.donated_bytes = sum(
                buffers[b] for b, i in param_buffers.items()
                if i in donated)
            report.constant_bytes = sum(
                nbytes for buf, nbytes in buffers.items()
                if comp.instructions.get(buf) is not None
                and comp.instructions[buf].opcode == "constant")
            if comp.root is not None:
                report.output_bytes = hlo_ir.result_bytes(comp.root)
            top = sorted(range(n), key=lambda p: live[p],
                         reverse=True)[:TOP_SETS]
            for p in top:
                members = sorted(
                    ((buf, nbytes) for buf, nbytes in buffers.items()
                     if defpos[buf] <= p <= lastuse[buf] and nbytes),
                    key=lambda kv: kv[1], reverse=True)[:_TOP_MEMBERS]
                if spike.get(p):
                    members = ([("(callee transients)", spike[p])]
                               + members)[:_TOP_MEMBERS]
                report.top_sets.append({
                    "position": p,
                    "instruction": instrs[p].name,
                    "live_bytes": live[p],
                    "members": members,
                })
        return peak, live, buffers, defpos, lastuse


def mem_report(hlo: stats.ModuleOrText, name: str = "program") -> MemReport:
    """Build the static memory certificate for one lowered program.
    Accepts raw HLO text (either print dialect) or a parsed Module."""
    module = stats._as_module(hlo)
    report = MemReport(name=name)
    entry = module.entry_computation
    if entry is None:
        report.notes.append("module has no computations")
        return report
    analyzer = _Analyzer(module)
    peak, _, _, _, _ = analyzer._sweep(
        entry, entry_mode=True, charge_root=True, stack=(entry.name,),
        donated=_donated_indices(module), report=report)
    report.peak_bytes = peak
    report.transient_peak_bytes = max(0, peak - report.param_bytes)
    return report


# ---------------------------------------------------------------------------
# Donation proven as an aliased-bytes equality
# ---------------------------------------------------------------------------

def _leaf_bytes(type_str: str) -> List[int]:
    """Byte sizes of every array LEAF in a (possibly nested tuple) type."""
    s = hlo_ir._TYPE_COMMENT_RE.sub("", type_str or "").strip()
    if not s:
        return []
    if s.startswith("("):
        inner = s[1:hlo_ir._scan_balanced(s, 0) - 1]
        out: List[int] = []
        for part in hlo_ir.split_top(inner):
            out.extend(_leaf_bytes(part))
        return out
    b = hlo_ir.type_bytes(s)
    return [b] if b else []


def donation_alias_findings(module: hlo_ir.Module,
                            program: str = "program") -> List[str]:
    """Prove each donated entry parameter can actually alias an output:
    every donated leaf's byte size must be matched by a DISTINCT root
    leaf of the same size (multiset containment).  A donated buffer with
    no same-size output leaf is a donation that cannot round-trip — XLA
    will quietly copy, and the in-place-update story is fiction."""
    donated = _donated_indices(module)
    entry = module.entry_computation
    if not donated or entry is None:
        return []
    by_index: Dict[int, str] = {}
    for ins in entry.instructions.values():
        if ins.opcode == "parameter" and ins.operand_raw:
            try:
                by_index[int(ins.operand_raw[0])] = ins.result_type
            except ValueError:
                pass
    root = entry.root
    pool: Dict[int, int] = {}
    for b in _leaf_bytes(root.result_type if root is not None else ""):
        pool[b] = pool.get(b, 0) + 1
    out: List[str] = []
    for idx in sorted(donated):
        for b in _leaf_bytes(by_index.get(idx, "")):
            if pool.get(b, 0) > 0:
                pool[b] -= 1
            else:
                out.append(
                    f"{program}: donated entry parameter {idx} "
                    f"({by_index.get(idx, '?')}, {b} bytes) has no "
                    f"same-size output leaf to alias — the donation "
                    f"cannot round-trip in place")
    return out


# ---------------------------------------------------------------------------
# Differential check against compiled.memory_analysis()
# ---------------------------------------------------------------------------

def check_against_compiled(report: MemReport, mem_stats, *,
                           band: float = COMPILED_BAND,
                           windowed: bool = False) -> List[str]:
    """Compare the static bound with JAX's ``CompiledMemoryStats``.
    The static peak must NEVER sit under the compiled temp+output bytes
    (an under-count would certify programs that OOM); on the windowed
    train paths it must also sit within ``band`` x the compiled total
    (argument+output+temp) — conservative is fine, unmoored is not."""
    temp = getattr(mem_stats, "temp_size_in_bytes", 0) or 0
    out_b = getattr(mem_stats, "output_size_in_bytes", 0) or 0
    args = getattr(mem_stats, "argument_size_in_bytes", 0) or 0
    findings: List[str] = []
    floor = temp + out_b
    if report.peak_bytes < floor:
        findings.append(
            f"{report.name}: static peak {report.peak_bytes} B UNDER the "
            f"compiled floor temp+output = {temp}+{out_b} = {floor} B — "
            f"the bound is unsound")
    total = args + out_b + temp
    if windowed and total and report.peak_bytes > band * total:
        findings.append(
            f"{report.name}: static peak {report.peak_bytes} B exceeds "
            f"{band:g}x the compiled total {total} B — the bound came "
            f"unmoored from the executable")
    return findings


# ---------------------------------------------------------------------------
# jax-free repo self-checks (tools/lint_graft.py path-less run)
# ---------------------------------------------------------------------------

#: The v5e datasheet literals and their single source of truth.  This
#: checker file is the one other place allowed to SPELL them (as the
#: patterns it greps for).
_HW_LITERALS = ("197e12", "819e9", "200e9")
_HW_HOME = os.path.join("cs744_ddp_tpu", "analysis", "costmodel.py")
_HW_CHECKER = os.path.join("cs744_ddp_tpu", "analysis", "memlife.py")
_CAPACITY_ASSIGN_RE = re.compile(r"^\s*V5E_HBM_CAPACITY_BYTES\s*=",
                                 re.MULTILINE)
_SCAN_DIRS = ("cs744_ddp_tpu", "tools")
_SCAN_FILES = ("bench.py",)

#: Committed fixture pair proving the donation delta in bytes: identical
#: windowed programs, one donating its carried state, one not.
FIXTURE_DONATED = os.path.join("tests", "assets", "hlo",
                               "memlife_window_donated.hlo")
FIXTURE_UNDONATED = os.path.join("tests", "assets", "hlo",
                                 "memlife_window_undonated.hlo")


def _py_files(repo_root: str):
    for d in _SCAN_DIRS:
        for dirpath, _, names in os.walk(os.path.join(repo_root, d)):
            for fn in names:
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)
    for fn in _SCAN_FILES:
        path = os.path.join(repo_root, fn)
        if os.path.exists(path):
            yield path


def check_constants_single_source(repo_root: str) -> List[LintFinding]:
    """The v5e roofline/capacity numbers live in analysis/costmodel.py
    and NOWHERE else — a second copy is a fork waiting to drift."""
    findings: List[LintFinding] = []
    home = os.path.join(repo_root, _HW_HOME)
    checker = os.path.join(repo_root, _HW_CHECKER)
    for path in _py_files(repo_root):
        if os.path.abspath(path) in (os.path.abspath(home),
                                     os.path.abspath(checker)):
            continue
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        except OSError:
            continue
        for lit in _HW_LITERALS:
            for m in re.finditer(re.escape(lit) + r"\b", text):
                line = text.count("\n", 0, m.start()) + 1
                findings.append(LintFinding(
                    "memory-constants", path, line,
                    f"v5e literal {lit} duplicated outside "
                    f"{_HW_HOME}; import it from analysis.costmodel"))
        for m in _CAPACITY_ASSIGN_RE.finditer(text):
            line = text.count("\n", 0, m.start()) + 1
            findings.append(LintFinding(
                "memory-constants", path, line,
                f"V5E_HBM_CAPACITY_BYTES reassigned outside {_HW_HOME}"))
    try:
        with open(home, encoding="utf-8") as f:
            home_text = f.read()
    except OSError:
        home_text = ""
    for lit in _HW_LITERALS:
        if len(re.findall(re.escape(lit) + r"\b", home_text)) != 1:
            findings.append(LintFinding(
                "memory-constants", home, 0,
                f"v5e literal {lit} must appear exactly once in its "
                f"home file"))
    if len(_CAPACITY_ASSIGN_RE.findall(home_text)) != 1:
        findings.append(LintFinding(
            "memory-constants", home, 0,
            "V5E_HBM_CAPACITY_BYTES must be assigned exactly once in "
            "its home file"))
    return findings


def check_fixture_invariants(repo_root: str) -> List[LintFinding]:
    """Re-prove the donation byte bound on the committed fixture pair:
    the non-donating windowed program's static peak must exceed the
    donating twin's by its carried state bytes, and the donating twin's
    donation must round-trip as an aliased-bytes equality."""
    findings: List[LintFinding] = []
    paths = {}
    for tag, rel in (("donated", FIXTURE_DONATED),
                     ("undonated", FIXTURE_UNDONATED)):
        path = os.path.join(repo_root, rel)
        if not os.path.exists(path):
            findings.append(LintFinding(
                "memory-fixture", path, 0,
                f"committed memlife fixture missing ({tag})"))
            continue
        with open(path, encoding="utf-8") as f:
            paths[tag] = (path, f.read())
    if len(paths) != 2:
        return findings
    don_path, don_text = paths["donated"]
    und_path, und_text = paths["undonated"]
    don = mem_report(don_text, "fixture/donated")
    und = mem_report(und_text, "fixture/undonated")
    if not und.undonated_copy_bytes:
        findings.append(LintFinding(
            "memory-fixture", und_path, 0,
            "non-donating windowed fixture charges no carry copy — the "
            "donation delta is no longer being proven"))
    if und.peak_bytes - don.peak_bytes != und.undonated_copy_bytes:
        findings.append(LintFinding(
            "memory-fixture", und_path, 0,
            f"donation delta broke: undonated peak {und.peak_bytes} - "
            f"donated peak {don.peak_bytes} != copy bytes "
            f"{und.undonated_copy_bytes}"))
    for msg in donation_alias_findings(stats._as_module(don_text),
                                       "fixture/donated"):
        findings.append(LintFinding("memory-fixture", don_path, 0, msg))
    return findings


def check_memory(repo_root: str) -> List[LintFinding]:
    """Everything the path-less lint run certifies about memory, with no
    jax import: constants single-sourcing + the fixture invariants."""
    return (check_constants_single_source(repo_root)
            + check_fixture_invariants(repo_root))
