"""AST lint for repo invariants the runtime can't see.

Four rules, each encoding a concurrency/measurement discipline this
codebase depends on but no test can reliably catch (the failure is a
silent mis-measurement or a rare race, not an exception):

- ``unfenced-timing`` — a wall-clock interval (``t0 = time.time()`` ...
  ``time.time() - t0``) that brackets an async dispatch
  (``train_window``/``train_step``/``infer``/...) must contain a fence
  (``block_until_ready``/``np.asarray``/``float()``/``.result()``/...)
  between the dispatch and the interval end; otherwise the timer measures
  dispatch latency, not execution (the round-3 verdict's critique of the
  reference's print timers).
- ``thread-jnp`` — producer/batcher THREAD bodies (any function passed as
  ``Thread(target=...)`` or to ``_prefetch_iter``) must not touch ``jnp``
  / ``jax.numpy``: tracing or device compute on the producer thread
  serializes against the main thread's dispatches and can deadlock under
  the staging watchdogs; producers stay numpy-only and hand off via
  ``device_put``-style transfer helpers.
- ``lock-ownership`` — within a class owning a ``threading.Lock`` /
  ``RLock`` / ``Condition``, any attribute EVER mutated under the lock is
  lock-owned; mutating it outside a ``with <lock>:`` block (``__init__``
  excepted) is a data race (this caught ``MicroBatcher.start`` writing
  ``_stop``/``_worker`` unlocked while ``_enqueue`` reads them under the
  lock — fixed in the same PR that added the rule).  A class can also
  DECLARE attributes lock-owned up front with a class-level
  ``_lock_owned = ("attr", ...)`` tuple — those are guarded from the
  first write on, whether or not a locked write is in view (the elastic
  coordinator declares its membership state this way, so a new method
  that mutates membership unlocked fails the lint even before any locked
  counterpart exists).  Two holding idioms are understood without
  waivers (round 13): a conditional acquire
  (``if not self._lock.acquire(...): return`` — the rest of the block
  runs held, the watcher's non-blocking poll), and ``*_locked``-suffixed
  methods, whose whole body runs under the caller's lock by contract —
  the suffix is TRUSTED here and VERIFIED by ``analysis/lockgraph.py``,
  which checks every call site of every ``*_locked`` method actually
  holds the class lock.

- ``span-hygiene`` — a span emitted under one of the distributed-trace
  names (``trace_client``/``frontend_request``/``wire_decode``/
  ``sched_queue``/``sched_defer``/``reply_encode``) must carry the
  trace-context join keys (``**ctx.attrs()`` or an explicit
  ``trace_id=``); batch-level engine spans (``serve_stage``/
  ``serve_dispatch``/``serve_fetch``) must carry their member batcher
  trace ids (``traces=``).  A span missing its keys still renders in
  single-process reports, but the cross-process waterfall silently
  loses that stage — exactly the failure no test sees.

Waiver: append ``# lint: ok`` to the offending line to waive every rule,
or ``# lint: ok(rule-name[, rule-name])`` to waive specific rules.  Run
standalone via ``tools/lint_graft.py`` (nonzero exit on findings); the
repo itself is kept clean by tests/test_analysis.py (tier 1).
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

DEFAULT_TARGETS = ("cs744_ddp_tpu", "tools", "bench.py")

# Calls that put work on an accelerator queue and return before it runs.
# ``infer_counts_async`` is the serving pipeline's explicit issue half:
# timing it without its ``complete`` fence measures enqueue, not service.
DISPATCH_NAMES = frozenset({
    "train_window", "train_step", "train_window_host", "train_step_host",
    "eval_window", "fwd_window", "infer", "infer_counts",
    "infer_counts_async"})
# Calls/conversions that synchronize host and device.  ``complete`` is
# the pipeline's completion fence (engine.complete(handle) blocks until
# the dispatched program finished).
FENCE_NAMES = frozenset({
    "block_until_ready", "asarray", "array", "device_get", "item",
    "result", "_fetch_step", "complete"})
FENCE_BUILTINS = frozenset({"float", "int", "bool"})
TIMER_ATTRS = frozenset({"time", "perf_counter", "monotonic"})
MUTATOR_METHODS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "update", "pop",
    "popleft", "remove", "discard", "clear", "setdefault"})
LOCK_FACTORIES = frozenset({"Lock", "RLock", "Condition", "Semaphore",
                            "BoundedSemaphore"})
THREAD_FEEDERS = frozenset({"_prefetch_iter"})

_WAIVE_RE = re.compile(r"#\s*lint:\s*ok(?:\(([^)]*)\))?")


@dataclass(frozen=True)
class LintFinding:
    rule: str
    path: str
    line: int
    message: str


def _waived(finding: LintFinding, source_lines: Sequence[str]) -> bool:
    if not (1 <= finding.line <= len(source_lines)):
        return False
    m = _WAIVE_RE.search(source_lines[finding.line - 1])
    if not m:
        return False
    rules = m.group(1)
    if rules is None:
        return True
    return finding.rule in {r.strip() for r in rules.split(",")}


def _call_name(node: ast.Call) -> Optional[str]:
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return None


# ---------------------------------------------------------------------------
# unfenced-timing
# ---------------------------------------------------------------------------

def _is_timer_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "time"
            and node.func.attr in TIMER_ATTRS)


def _check_unfenced_timing(tree: ast.AST, path: str) -> List[LintFinding]:
    findings: List[LintFinding] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        timers: Dict[str, int] = {}          # var -> start line
        elapsed: List[Tuple[str, int]] = []  # (var, line)
        dispatches: List[Tuple[str, int]] = []
        fences: List[int] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and _is_timer_call(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        timers.setdefault(t.id, node.lineno)
            elif (isinstance(node, ast.BinOp)
                  and isinstance(node.op, ast.Sub)
                  and isinstance(node.right, ast.Name)
                  and _is_timer_call(node.left)):
                elapsed.append((node.right.id, node.lineno))
            elif isinstance(node, ast.Call):
                name = _call_name(node)
                if name in DISPATCH_NAMES:
                    dispatches.append((name, node.lineno))
                if name in FENCE_NAMES or name in FENCE_BUILTINS:
                    # A fence that WRAPS the dispatch starts on an earlier
                    # line; it synchronizes where it returns, so record
                    # its end line.
                    fences.append(getattr(node, "end_lineno", node.lineno))
        for var, end_line in elapsed:
            start_line = timers.get(var)
            if start_line is None or end_line <= start_line:
                continue
            for name, d_line in dispatches:
                if not (start_line < d_line <= end_line):
                    continue
                if not any(d_line <= f <= end_line for f in fences):
                    findings.append(LintFinding(
                        "unfenced-timing", path, d_line,
                        f"dispatch {name}() timed by "
                        f"{var!r} ({start_line}..{end_line}) with no "
                        f"fence (block_until_ready/asarray/float/...) "
                        f"before the interval ends — the timer measures "
                        f"dispatch, not execution"))
        # A timer interval containing NO dispatch is plain host timing —
        # out of scope by construction.
    return findings


# ---------------------------------------------------------------------------
# thread-jnp
# ---------------------------------------------------------------------------

def _thread_entry_names(tree: ast.AST) -> Set[str]:
    names: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        callee = _call_name(node)
        if callee == "Thread":
            for kw in node.keywords:
                if kw.arg != "target":
                    continue
                if isinstance(kw.value, ast.Name):
                    names.add(kw.value.id)
                elif isinstance(kw.value, ast.Attribute):
                    names.add(kw.value.attr)
        elif callee in THREAD_FEEDERS:
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    names.add(arg.id)
    return names


def _check_thread_jnp(tree: ast.AST, path: str) -> List[LintFinding]:
    entries = _thread_entry_names(tree)
    if not entries:
        return []
    findings: List[LintFinding] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if fn.name not in entries:
            continue
        for node in ast.walk(fn):
            bad = None
            if isinstance(node, ast.Name) and node.id == "jnp":
                bad = "jnp"
            elif (isinstance(node, ast.Attribute)
                  and isinstance(node.value, ast.Name)
                  and node.value.id == "jax" and node.attr == "numpy"):
                bad = "jax.numpy"
            if bad is not None:
                findings.append(LintFinding(
                    "thread-jnp", path, node.lineno,
                    f"{bad} used inside thread entry {fn.name!r}: "
                    f"producer/batcher threads must stay numpy-only "
                    f"(tracing on a producer thread serializes against "
                    f"the main thread's dispatches)"))
    return findings


# ---------------------------------------------------------------------------
# lock-ownership
# ---------------------------------------------------------------------------

def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
    locks: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        if not (isinstance(node.value, ast.Call)
                and _call_name(node.value) in LOCK_FACTORIES):
            continue
        for t in node.targets:
            attr = _self_attr(t)
            if attr:
                locks.add(attr)
    return locks


def _declared_lock_owned(cls: ast.ClassDef) -> Set[str]:
    """Attributes the class PROMISES to mutate only under its lock, via a
    class-level ``_lock_owned = ("attr", ...)`` tuple/list of string
    literals.  Non-literal elements are ignored (the declaration must be
    statically readable to mean anything here)."""
    owned: Set[str] = set()
    for stmt in cls.body:
        if not isinstance(stmt, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "_lock_owned"
                   for t in stmt.targets):
            continue
        if isinstance(stmt.value, (ast.Tuple, ast.List)):
            owned |= {el.value for el in stmt.value.elts
                      if isinstance(el, ast.Constant)
                      and isinstance(el.value, str)}
    return owned


def _attr_writes_in_stmt(stmt: ast.stmt) -> List[Tuple[str, int]]:
    """self-attribute mutations in ONE statement (not descending into
    nested statements): assignments, augmented assignments, ``del``
    of/into the attribute, and mutating method calls like
    ``self.q.append(x)``."""
    writes: List[Tuple[str, int]] = []

    def target_attr(t: ast.AST) -> Optional[str]:
        attr = _self_attr(t)
        if attr:
            return attr
        if isinstance(t, (ast.Subscript, ast.Starred)):
            return target_attr(t.value)
        if isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                a = target_attr(el)
                if a:
                    writes.append((a, t.lineno))
        return None

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            a = target_attr(t)
            if a:
                writes.append((a, stmt.lineno))
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        a = target_attr(stmt.target)
        if a:
            writes.append((a, stmt.lineno))
    elif isinstance(stmt, ast.Delete):
        for t in stmt.targets:
            a = target_attr(t)
            if a:
                writes.append((a, stmt.lineno))
    elif isinstance(stmt, ast.Expr):
        for node in ast.walk(stmt):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in MUTATOR_METHODS):
                a = _self_attr(node.func.value)
                if a:
                    writes.append((a, node.lineno))
    return writes


def _stmt_acquires(stmt: ast.stmt, locks: Set[str]) -> bool:
    """True when the statement's own expressions (not nested blocks)
    contain a ``self.<lock>.acquire(...)`` call — the conditional-acquire
    idiom: the failure arm bails out, so the REST of the enclosing block
    runs with the lock held."""
    for fname, value in ast.iter_fields(stmt):
        if fname in ("body", "orelse", "finalbody", "handlers"):
            continue
        nodes = value if isinstance(value, list) else [value]
        for n in nodes:
            if not isinstance(n, ast.AST):
                continue
            for sub in ast.walk(n):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "acquire"
                        and _self_attr(sub.func.value) in locks):
                    return True
    return False


def _collect_writes(method: ast.FunctionDef, locks: Set[str],
                    base_locked: bool = False
                    ) -> List[Tuple[str, int, bool]]:
    """(attr, line, under_lock) for every self-attribute mutation."""
    out: List[Tuple[str, int, bool]] = []

    def visit_block(stmts, locked: bool):
        for stmt in stmts:
            for attr, line in _attr_writes_in_stmt(stmt):
                out.append((attr, line, locked))
            if isinstance(stmt, ast.With):
                inner = locked or any(
                    _self_attr(item.context_expr) in locks
                    for item in stmt.items)
                visit_block(stmt.body, inner)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue   # nested defs execute later, on their own terms
            else:
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, field, None)
                    if sub:
                        visit_block(sub, locked)
                for handler in getattr(stmt, "handlers", ()):
                    visit_block(handler.body, locked)
            if not locked and _stmt_acquires(stmt, locks):
                locked = True
    visit_block(method.body, base_locked)
    return out


def _check_lock_ownership(tree: ast.AST, path: str) -> List[LintFinding]:
    findings: List[LintFinding] = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        locks = _lock_attrs(cls)
        if not locks:
            continue
        per_method: Dict[str, List[Tuple[str, int, bool]]] = {}
        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # *_locked methods run entirely under the caller's lock;
                # analysis/lockgraph.py verifies every call site holds it.
                per_method[item.name] = _collect_writes(
                    item, locks, base_locked=item.name.endswith("_locked"))
        owned: Set[str] = {
            attr
            for method, writes in per_method.items()
            for attr, _, locked in writes if locked}
        owned |= _declared_lock_owned(cls)
        owned -= locks   # the lock attribute itself is not guarded by itself
        for method, writes in per_method.items():
            if method == "__init__":
                continue   # construction happens-before any sharing
            for attr, line, locked in writes:
                if attr in owned and not locked:
                    findings.append(LintFinding(
                        "lock-ownership", path, line,
                        f"{cls.name}.{method} writes self.{attr} outside "
                        f"the owning lock ({'/'.join(sorted(locks))}) — "
                        f"it is mutated under the lock elsewhere, so this "
                        f"write races"))
    return findings


# ---------------------------------------------------------------------------
# span-hygiene
# ---------------------------------------------------------------------------

# The distributed-trace span vocabulary (obs/aggregate.py's contract).
# Per-request spans must carry the TraceContext join keys
# (trace_id/span_id/parent_span_id via ``**ctx.attrs()``); batch-level
# engine spans must carry the member batcher trace ids (``traces=``).
TRACED_SPAN_NAMES = frozenset({
    "trace_client", "frontend_request", "wire_decode", "sched_queue",
    "sched_defer", "reply_encode"})
BATCH_SPAN_NAMES = frozenset({"serve_stage", "serve_dispatch",
                              "serve_fetch"})


def _attrs_splat_names(fn: ast.AST) -> Set[str]:
    """Local names assigned from an ``<expr>.attrs()`` call inside this
    function — splatting one of these carries the trace context too."""
    names: Set[str] = set()
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Attribute)
                and node.value.func.attr == "attrs"):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
    return names


def _check_span_hygiene(tree: ast.AST, path: str) -> List[LintFinding]:
    """A span emitted under one of the distributed-trace names without
    its join keys is invisible to the cross-process aggregation — the
    waterfall silently loses that stage.  No test catches it (the span
    still renders in single-process reports), hence the lint."""
    findings: List[LintFinding] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        attrs_vars = _attrs_splat_names(fn)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if _call_name(node) not in ("span", "span_event"):
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            span = node.args[0].value
            kwargs = {kw.arg for kw in node.keywords if kw.arg}
            has_ctx_splat = any(
                kw.arg is None
                and ((isinstance(kw.value, ast.Call)
                      and isinstance(kw.value.func, ast.Attribute)
                      and kw.value.func.attr == "attrs")
                     or (isinstance(kw.value, ast.Name)
                         and kw.value.id in attrs_vars))
                for kw in node.keywords)
            if span in TRACED_SPAN_NAMES \
                    and not (has_ctx_splat or "trace_id" in kwargs):
                findings.append(LintFinding(
                    "span-hygiene", path, node.lineno,
                    f"span {span!r} emitted without trace-context attrs "
                    f"(**ctx.attrs() or trace_id=...) — the cross-process "
                    f"waterfall cannot join it"))
            elif span in BATCH_SPAN_NAMES \
                    and not (has_ctx_splat or "traces" in kwargs
                             or "trace_id" in kwargs):
                findings.append(LintFinding(
                    "span-hygiene", path, node.lineno,
                    f"batch span {span!r} emitted without traces= (member "
                    f"batcher trace ids) — requests cannot be joined to "
                    f"this dispatch"))
    return findings


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------

RULES = (_check_unfenced_timing, _check_thread_jnp, _check_lock_ownership,
         _check_span_hygiene)


def lint_source(source: str, path: str = "<source>") -> List[LintFinding]:
    tree = ast.parse(source)
    lines = source.splitlines()
    findings: List[LintFinding] = []
    for rule in RULES:
        findings.extend(rule(tree, path))
    return sorted((f for f in findings if not _waived(f, lines)),
                  key=lambda f: (f.path, f.line, f.rule))


def lint_file(path: str) -> List[LintFinding]:
    with open(path, encoding="utf-8") as fh:
        return lint_source(fh.read(), path)


def lint_paths(paths: Sequence[str]) -> List[LintFinding]:
    findings: List[LintFinding] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        findings.extend(
                            lint_file(os.path.join(dirpath, name)))
        elif p.endswith(".py"):
            findings.extend(lint_file(p))
    return findings
