"""Rule engine certifying every compiled program's cost shape.

The paper's subject IS the cost structure of each gradient-sync tier —
gather→scatter pays two chained collectives per leaf with world-x traffic,
per-param all-reduce one per leaf, bucketed DDP one per ~25 MB bucket —
and until now that structure was only *reported* (bench ``spectrum``),
never *checked*.  This module audits the pre-optimization HLO (via the
:mod:`analysis.hlo_ir` graph IR) plus the jaxpr of each shipped program
against a declared :class:`ProgramContract`, so a regression in comms
shape, precision, buffer donation or host syncs fails CI before any
hardware run.

Rules (each one catches a deliberately seeded violation in
tests/test_analysis.py):

- ``collective-contract`` — per-strategy count / byte / chain-depth
  certification: ``single`` (and every world-1 or serving program)
  lowers zero collectives; ``gather`` >= nleaves all-gathers with
  world-amplified result bytes and a 2-per-leaf chain; ``allreduce``
  >= nleaves all-reduces chained >= nleaves deep; ``ddp`` all-reduces
  chained exactly per-bucket — STRICTLY shallower than per-param when
  there are fewer buckets than leaves (the DDP fusion win, Li et al.,
  VLDB 2020).  The ``overlap`` tier keeps ddp's bucket count but must
  lower a chain depth of exactly 1 (no collective consumes another's
  result — the single post-backward chain is what defeats XLA's
  latency-hiding scheduler) and at least one bucket's operand cone must
  exclude part of the backward (``stats.collective_dot_cones``).  The
  compressed tiers (``compress-bf16`` / ``compress-int8`` /
  ``powersgd``) must keep their gradient wire bytes under
  ``param_bytes / compress_ratio`` (+ declared ``aux_bytes`` for BN
  pmeans, loss psums and the int8 shared-scale pmax): >= 2x / 4x /
  rank-r low-rank reduction vs the per-param f32 floor, certified on
  the lowering, not the docs.  The cross-strategy depth ladder
  (ddp < allreduce < gather) is certified whenever several strategies
  are audited together.
- ``dtype-leak`` — no f32/f64 ``dot``/``convolution`` in a
  bf16-declared program (a silent promotion doubles MXU cost).
- ``donation`` — programs declared to donate the train state must
  donate >= n_state_leaves entry buffers (``buffer_donor`` /
  ``input_output_alias`` module header); a miss doubles peak HBM.
  Donation is additionally proven as an ALIASED-BYTES equality
  (:func:`memlife.donation_alias_findings`): every donated entry
  buffer must have a same-size output leaf to alias, or XLA quietly
  copies and the in-place update is fiction.
- ``peak-memory`` — the static buffer-liveness bound
  (:func:`memlife.mem_report`) must fit the contract's
  ``hbm_budget_bytes`` (default: the single-sourced v5e chip capacity,
  :data:`costmodel.V5E_HBM_CAPACITY_BYTES`).  The fattest live set is
  named in the finding, so an over-budget program says WHAT to shrink.
- ``host-sync`` — no infeed/outfeed/send/recv or host-callback
  custom-calls inside ``while`` bodies (HLO side), and no callback
  primitives inside ``scan``/``while`` sub-jaxprs (jaxpr side): a host
  round-trip per scanned step serializes the window pipeline.
- ``baked-constants`` — no single constant larger than the contract's
  ``max_constant_bytes`` baked into the executable (weights and data
  must arrive as arguments, not literals).
- ``ingest-edge`` — programs declaring ``u8_edge`` (the serving ladder's
  fused-ingest rungs) must take the raw uint8 image bytes as an entry
  parameter and convert them to float IN-program: a float image-shaped
  entry parameter means the normalize leaked back to the host (one
  full-size f32 copy per request), and a missing u8->float convert
  means the program isn't consuming the wire bytes it claims to.

Waiver syntax (CLI ``--audit-waive``, bench, tests): ``RULE`` waives a
rule everywhere, ``RULE@GLOB`` only for programs matching the fnmatch
glob, e.g. ``baked-constants@serve/*``.  Waived findings are still
reported and recorded in the telemetry manifest, they just don't fail
``--audit strict``.
"""

from __future__ import annotations

import fnmatch
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from . import costmodel, hlo_ir, memlife, stats

DEFAULT_MAX_CONSTANT_BYTES = 1 << 20     # 1 MiB: far above any mask/iota
                                         # table, far below weights/data

_HOST_SYNC_OPS = frozenset(
    {"infeed", "outfeed", "send", "recv", "send-done", "recv-done"})
_CALLBACK_TARGET_RE = re.compile(r"callback|host", re.IGNORECASE)
_LOOP_PRIMITIVES = frozenset({"while", "scan"})


@dataclass(frozen=True)
class Finding:
    rule: str
    program: str
    message: str


@dataclass
class ProgramContract:
    """What a program's lowering is REQUIRED to look like."""
    name: str
    strategy: Optional[str] = None       # single/gather/allreduce/ddp/
                                         # overlap/compress-*/powersgd/eval;
                                         # None = no collectives expected
    world: int = 1
    nleaves: int = 0                     # parameter (grad) leaves
    nbuckets: int = 0                    # ddp bucket count
    param_bytes: int = 0                 # total parameter bytes (f32 master)
    n_state_leaves: int = 0              # TrainState leaves (donation floor)
    donates_state: bool = False
    precision: str = "f32"
    max_constant_bytes: int = DEFAULT_MAX_CONSTANT_BYTES
    compress_ratio: float = 1.0          # required param_bytes / grad wire
    aux_bytes: int = 0                   # non-gradient collective allowance
                                         # (BN pmean, loss psum, int8 pmax)
    u8_edge: bool = False                # fused-ingest contract: uint8
                                         # images at the program edge,
                                         # normalize in-program
    hbm_budget_bytes: int = 0            # static peak-HBM budget; 0 =
                                         # the v5e chip capacity


@dataclass
class AuditReport:
    program: str
    findings: List[Finding] = field(default_factory=list)
    waived: List[Finding] = field(default_factory=list)
    rules: Dict[str, str] = field(default_factory=dict)  # rule -> pass/fail/waived
    stats: Dict = field(default_factory=dict)            # collective shape record

    @property
    def passed(self) -> bool:
        return not self.findings


def _waived(finding: Finding, waivers: Sequence[str]) -> bool:
    for w in waivers:
        rule, _, prog_glob = w.partition("@")
        if rule != finding.rule:
            continue
        if not prog_glob or fnmatch.fnmatch(finding.program, prog_glob):
            return True
    return False


# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------

def _rule_collective_contract(module: hlo_ir.Module, jaxpr,
                              c: ProgramContract) -> List[Finding]:
    s = stats.collective_stats(module)
    by = stats.collective_bytes(module)
    depth = stats.collective_chain_depth(module)
    counts = {op: e["count"] for op, e in s["ops"].items()}
    total = s["total_count"]
    out: List[Finding] = []

    def bad(msg: str) -> None:
        out.append(Finding("collective-contract", c.name, msg))

    if c.strategy is None or c.strategy == "single":
        if total:
            bad(f"expected a collective-free program, found {counts} "
                f"(chain depth {depth})")
        return out
    if c.world <= 1:
        # A grad-sync strategy degraded to a one-chip world (the elastic
        # single-rank fallback) keeps its psums; over a single replica
        # they are no-ops, not contract violations.
        return out

    ar = counts.get("all-reduce", 0)
    ag = counts.get("all-gather", 0)
    others = {op: n for op, n in counts.items()
              if op not in ("all-reduce", "all-gather")}

    if c.strategy == "eval":
        if ag or others:
            bad(f"eval must reduce only (all-reduce); found {counts}")
        if ar < 1:
            bad("eval on a multi-device mesh must psum its counts; "
                "found no all-reduce")
        if depth > 2:
            bad(f"eval collective chain depth {depth} > 2: eval reductions "
                f"must not serialize")
        return out

    if c.strategy == "gather":
        if ag < c.nleaves:
            bad(f"gather tier must all-gather every grad leaf: "
                f"{ag} all-gather < {c.nleaves} leaves")
        if ar < 1:
            bad("gather tier reduces gathered grads; found no all-reduce")
        if depth < 2 * c.nleaves:
            bad(f"gather tier chains two collectives per leaf: depth "
                f"{depth} < {2 * c.nleaves}")
        want = c.world * c.param_bytes
        if c.param_bytes and by.get("all-gather", 0) < want:
            bad(f"gather traffic amplification missing: all-gather result "
                f"bytes {by.get('all-gather', 0)} < world x params = {want}")
        return out

    if c.strategy == "allreduce":
        if ag or others:
            bad(f"per-param all-reduce tier must emit only all-reduce; "
                f"found {counts}")
        if ar < c.nleaves:
            bad(f"per-param tier reduces every leaf: {ar} all-reduce < "
                f"{c.nleaves} leaves")
        if depth < c.nleaves:
            bad(f"per-param tier chains one collective per leaf: depth "
                f"{depth} < {c.nleaves}")
        if c.param_bytes and by.get("all-reduce", 0) < c.param_bytes:
            bad(f"all-reduce result bytes {by.get('all-reduce', 0)} < "
                f"total param bytes {c.param_bytes}")
        return out

    if c.strategy == "ddp":
        if ag or others:
            bad(f"ddp tier must emit only all-reduce; found {counts}")
        if ar < c.nbuckets:
            bad(f"ddp tier reduces every bucket: {ar} all-reduce < "
                f"{c.nbuckets} buckets")
        if depth < c.nbuckets:
            bad(f"ddp chain depth {depth} < {c.nbuckets} buckets")
        if c.nleaves > c.nbuckets and depth >= c.nleaves:
            bad(f"ddp fusion win lost: chain depth {depth} >= {c.nleaves} "
                f"leaves — bucketed reduces are serializing per leaf")
        if c.param_bytes and by.get("all-reduce", 0) < c.param_bytes:
            bad(f"all-reduce result bytes {by.get('all-reduce', 0)} < "
                f"total param bytes {c.param_bytes}")
        return out

    if c.strategy == "overlap":
        if ag or others:
            bad(f"overlapped tier must emit only all-reduce; found {counts}")
        if ar < c.nbuckets:
            bad(f"overlapped tier reduces every bucket: {ar} all-reduce < "
                f"{c.nbuckets} buckets")
        if depth > 1:
            bad(f"overlapped tier must not chain collectives: chain depth "
                f"{depth} > 1 — a single post-backward chain pins every "
                f"bucket behind the full backward and defeats latency "
                f"hiding")
        cones = stats.collective_dot_cones(module)
        if cones["total_dots"] and cones["min_cone"] >= cones["total_dots"]:
            bad(f"every collective's operand cone spans all "
                f"{cones['total_dots']} dots — no bucket reduce can be "
                f"issued before the backward completes")
        if c.param_bytes and by.get("all-reduce", 0) < c.param_bytes:
            bad(f"all-reduce result bytes {by.get('all-reduce', 0)} < "
                f"total param bytes {c.param_bytes}")
        return out

    if c.strategy in ("compress-bf16", "compress-int8", "powersgd"):
        if ag or others:
            bad(f"compressed tier must emit only all-reduce; found {counts}")
        if ar < c.nleaves:
            bad(f"compressed tier reduces every leaf: {ar} all-reduce < "
                f"{c.nleaves} leaves")
        wire = by.get("all-reduce", 0)
        if wire <= 0:
            bad("compressed tier lowered no all-reduce bytes")
        if c.param_bytes:
            grad_wire = max(0, wire - c.aux_bytes)
            ceiling = c.param_bytes / c.compress_ratio
            if grad_wire > ceiling:
                bad(f"compression is not real: gradient wire bytes "
                    f"{grad_wire} (total all-reduce {wire} - aux "
                    f"{c.aux_bytes}) exceed param_bytes / "
                    f"{c.compress_ratio:g}x = {ceiling:.0f}")
        return out

    bad(f"unknown strategy {c.strategy!r} in contract")
    return out


def _result_dtype(ins: hlo_ir.Instruction) -> Optional[str]:
    m = stats._SHAPE_RE.search(ins.result_type)
    return m.group(1) if m else None


def _rule_dtype_leak(module: hlo_ir.Module, jaxpr,
                     c: ProgramContract) -> List[Finding]:
    if c.precision != "bf16":
        return []
    out = []
    for ins in module.instructions():
        if ins.opcode in ("dot", "convolution") and \
                _result_dtype(ins) in ("f32", "f64"):
            out.append(Finding(
                "dtype-leak", c.name,
                f"{_result_dtype(ins)} {ins.opcode} {ins.name!r} in a "
                f"bf16-declared program (silent promotion doubles MXU "
                f"cost): {ins.result_type}"))
    return out


def _rule_donation(module: hlo_ir.Module, jaxpr,
                   c: ProgramContract) -> List[Finding]:
    out: List[Finding] = []
    # Aliased-bytes round-trip: whatever IS donated must be provably
    # aliasable, declared or not.
    for msg in memlife.donation_alias_findings(module, c.name):
        out.append(Finding("donation", c.name, msg))
    if not c.donates_state:
        return out
    n = module.donated_param_count()
    if n < c.n_state_leaves:
        out.append(Finding(
            "donation", c.name,
            f"declared to donate the train state but only {n} of >= "
            f"{c.n_state_leaves} entry buffers are donated "
            f"(buffer_donor/input_output_alias) — un-donated state "
            f"doubles peak HBM"))
    return out


def _rule_peak_memory(module: hlo_ir.Module, jaxpr,
                      c: ProgramContract) -> List[Finding]:
    budget = c.hbm_budget_bytes or costmodel.V5E_HBM_CAPACITY_BYTES
    rep = memlife.mem_report(module, c.name)
    if rep.peak_bytes <= budget:
        return []
    top = rep.top_sets[0] if rep.top_sets else {}
    fattest = ", ".join(
        f"{n}={b}" for n, b in top.get("members", [])[:4])
    return [Finding(
        "peak-memory", c.name,
        f"static peak HBM {rep.peak_bytes} B "
        f"({rep.peak_bytes / 2**20:.1f} MiB) exceeds the "
        f"{budget} B budget; fattest live set at "
        f"{top.get('instruction', '?')!r}: {fattest}")]


def _while_reachable(module: hlo_ir.Module) -> set:
    """Names of computations reachable from any ``while`` body/condition."""
    seeds = []
    for ins in module.instructions():
        if ins.opcode == "while":
            seeds.extend(ins.called)
    seen = set()
    stack = list(seeds)
    while stack:
        name = stack.pop()
        if name in seen or name not in module.computations:
            continue
        seen.add(name)
        for ins in module.computations[name].instructions.values():
            stack.extend(ins.called)
    return seen


def _jaxpr_host_syncs(jaxpr, in_loop: bool = False) -> List[str]:
    hits: List[str] = []
    for eqn in getattr(jaxpr, "eqns", ()):
        prim = eqn.primitive.name
        inner_loop = in_loop or prim in _LOOP_PRIMITIVES
        if in_loop and ("callback" in prim or prim in ("infeed", "outfeed")):
            hits.append(prim)
        for v in eqn.params.values():
            for x in (v if isinstance(v, (list, tuple)) else (v,)):
                sub = getattr(x, "jaxpr", x)
                if hasattr(sub, "eqns"):
                    hits.extend(_jaxpr_host_syncs(sub, inner_loop))
    return hits


def _rule_host_sync(module: hlo_ir.Module, jaxpr,
                    c: ProgramContract) -> List[Finding]:
    out: List[Finding] = []
    loop_comps = _while_reachable(module)
    for cname in loop_comps:
        for ins in module.computations[cname].instructions.values():
            target = ins.attr("custom_call_target") or ""
            if ins.opcode in _HOST_SYNC_OPS or (
                    ins.opcode == "custom-call"
                    and _CALLBACK_TARGET_RE.search(target)):
                out.append(Finding(
                    "host-sync", c.name,
                    f"host sync {ins.opcode} {ins.name!r}"
                    f"{' -> ' + target if target else ''} inside loop "
                    f"computation {cname!r}: one host round-trip per "
                    f"scanned step serializes the window"))
    if jaxpr is not None:
        for prim in _jaxpr_host_syncs(getattr(jaxpr, "jaxpr", jaxpr)):
            out.append(Finding(
                "host-sync", c.name,
                f"callback primitive {prim!r} inside a scan/while body "
                f"(jaxpr)"))
    return out


def _rule_baked_constants(module: hlo_ir.Module, jaxpr,
                          c: ProgramContract) -> List[Finding]:
    out = []
    for ins in module.instructions():
        if ins.opcode != "constant":
            continue
        b = stats.bytes_of_type(ins.result_type)
        if b > c.max_constant_bytes:
            out.append(Finding(
                "baked-constants", c.name,
                f"constant {ins.name!r} bakes {b} bytes "
                f"({ins.result_type}) into the executable "
                f"(> {c.max_constant_bytes}); pass it as an argument"))
    return out


_IMG_SHAPE_RE = re.compile(r"\b(u8|f16|bf16|f32|f64)\[\d+,32,32,3\]")
_FLOAT_DTYPES = ("f16", "bf16", "f32", "f64")


def _rule_ingest_edge(module: hlo_ir.Module, jaxpr,
                      c: ProgramContract) -> List[Finding]:
    if not c.u8_edge:
        return []
    out: List[Finding] = []
    entry = module.entry_computation
    if entry is None:
        return [Finding("ingest-edge", c.name,
                        "program has no entry computation to certify")]
    u8_img = False
    for ins in entry.instructions.values():
        if ins.opcode != "parameter":
            continue
        m = _IMG_SHAPE_RE.search(ins.result_type)
        if m is None:
            continue
        if m.group(1) == "u8":
            u8_img = True
        else:
            out.append(Finding(
                "ingest-edge", c.name,
                f"{m.group(1)} image-shaped entry parameter {ins.name!r} "
                f"({ins.result_type}): the wire-to-device path must stay "
                f"uint8 — a float image input means the normalize left "
                f"the program and the host pays a 4x transfer"))
    if not u8_img:
        out.append(Finding(
            "ingest-edge", c.name,
            "no uint8 image-shaped entry parameter: a fused-ingest rung "
            "must take the raw u8 wire bytes at the program edge"))
        return out
    types = {ins.name: ins.result_type for ins in module.instructions()}
    converted = any(
        ins.opcode == "convert"
        and _result_dtype(ins) in _FLOAT_DTYPES
        and any(types.get(op, "").lstrip().startswith("u8[")
                for op in ins.operands)
        for ins in module.instructions())
    if not converted:
        out.append(Finding(
            "ingest-edge", c.name,
            "no in-program u8 -> float convert: the program takes uint8 "
            "images but never normalizes them on device"))
    return out


RULES = {
    "collective-contract": _rule_collective_contract,
    "dtype-leak": _rule_dtype_leak,
    "donation": _rule_donation,
    "host-sync": _rule_host_sync,
    "baked-constants": _rule_baked_constants,
    "ingest-edge": _rule_ingest_edge,
    "peak-memory": _rule_peak_memory,
}


def audit_program(hlo_text: str, contract: ProgramContract, jaxpr=None,
                  waive: Sequence[str] = ()) -> AuditReport:
    """Run every rule over one program's lowering (+ optional jaxpr)."""
    module = hlo_ir.parse(hlo_text)
    report = AuditReport(program=contract.name)
    s = stats.collective_stats(module)
    report.stats = {
        "collectives": {op: e["count"] for op, e in s["ops"].items()},
        "result_bytes": stats.collective_bytes(module),
        "chain_depth": stats.collective_chain_depth(module),
        "donated": module.donated_param_count(),
        "peak_mib": round(
            memlife.mem_report(module, contract.name).peak_bytes / 2**20,
            3),
    }
    for rule, fn in RULES.items():
        findings = fn(module, jaxpr, contract)
        kept = [f for f in findings if not _waived(f, waive)]
        dropped = [f for f in findings if _waived(f, waive)]
        report.findings.extend(kept)
        report.waived.extend(dropped)
        report.rules[rule] = ("fail" if kept else
                              "waived" if dropped else "pass")
    return report


# ---------------------------------------------------------------------------
# The program zoo: every shipped program, lowered and audited
# ---------------------------------------------------------------------------

@dataclass
class AuditResult:
    reports: List[AuditReport] = field(default_factory=list)
    ladder: Dict = field(default_factory=dict)
    ladder_findings: List[Finding] = field(default_factory=list)
    # Program name -> pre-optimization HLO text, kept only when the caller
    # asks (``collect_hlo``) — the attribution pipeline (analysis/costmodel)
    # re-walks the same lowerings the audit certified.
    hlo: Dict[str, str] = field(default_factory=dict)

    @property
    def clean(self) -> bool:
        return (not self.ladder_findings
                and all(r.passed for r in self.reports))

    def findings(self) -> List[Finding]:
        out = [f for r in self.reports for f in r.findings]
        out.extend(self.ladder_findings)
        return out

    def waived(self) -> List[Finding]:
        return [f for r in self.reports for f in r.waived]

    def summary(self) -> Dict:
        """Manifest/bench-ready record: per-program rule pass/fail +
        waivers, the strategy depth ladder, and every finding message."""
        return {
            "clean": self.clean,
            "n_programs": len(self.reports),
            "n_findings": len(self.findings()),
            "n_waived": len(self.waived()),
            "programs": {
                r.program: {"rules": r.rules, **r.stats}
                for r in self.reports},
            "findings": [
                {"rule": f.rule, "program": f.program,
                 "message": f.message[:300]}
                for f in self.findings()],
            "waived": [
                {"rule": f.rule, "program": f.program,
                 "message": f.message[:300]}
                for f in self.waived()],
            **({"ladder": self.ladder} if self.ladder else {}),
        }

    def format_lines(self) -> List[str]:
        lines = []
        for r in self.reports:
            mark = "PASS" if r.passed else "FAIL"
            extra = f"  waived={len(r.waived)}" if r.waived else ""
            lines.append(f"[audit] {mark} {r.program}  "
                         f"collectives={r.stats.get('collectives', {})} "
                         f"depth={r.stats.get('chain_depth')}{extra}")
            for f in r.findings + r.waived:
                tag = "waived " if f in r.waived else ""
                lines.append(f"[audit]   {tag}{f.rule}: {f.message}")
        for f in self.ladder_findings:
            lines.append(f"[audit] FAIL {f.program} {f.rule}: {f.message}")
        if self.ladder:
            lines.append(f"[audit] strategy depth ladder: {self.ladder}")
        lines.append(f"[audit] {'CLEAN' if self.clean else 'DIRTY'}: "
                     f"{len(self.reports)} programs, "
                     f"{len(self.findings())} findings, "
                     f"{len(self.waived())} waived")
        return lines


def _certify_ladder(depths: Dict[str, int], nleaves: int, nbuckets: int,
                    program: str) -> Tuple[Dict, List[Finding]]:
    """Cross-strategy certification: the paper's ordering of chain depths
    (bucketed ddp < per-param allreduce < chained gather) must hold on
    the lowered programs themselves whenever several tiers are audited
    together on a multi-device mesh."""
    ladder = dict(depths)
    findings: List[Finding] = []

    def bad(msg):
        findings.append(Finding("collective-contract", program, msg))

    if "allreduce" in depths and "gather" in depths:
        if not depths["gather"] > depths["allreduce"]:
            bad(f"gather depth {depths['gather']} must exceed allreduce "
                f"depth {depths['allreduce']} (two chained collectives "
                f"per leaf vs one)")
    if "allreduce" in depths and "ddp" in depths and nleaves > nbuckets:
        if not depths["ddp"] < depths["allreduce"]:
            bad(f"ddp depth {depths['ddp']} must be shallower than "
                f"allreduce depth {depths['allreduce']} with {nbuckets} "
                f"buckets over {nleaves} leaves")
    return ladder, findings


def _train_sds(mesh, state_sds, global_batch: int, window: int,
               ring_capacity: int = 0):
    """ShapeDtypeStructs for the train step/window/eval signatures on
    ``mesh`` (mirrors the Trainer's staging shapes).  ``ring_capacity``
    > 0 adds the metric-ring pair (obs/ringbuf.py) the ring-carrying
    window variants take as their donated second argument."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    rep = NamedSharding(mesh, P())
    row = NamedSharding(mesh, P("data"))
    epoch = NamedSharding(mesh, P(None, "data"))

    def share(sds, sharding):
        return jax.ShapeDtypeStruct(sds.shape, sds.dtype, sharding=sharding)

    state = jax.tree_util.tree_map(lambda s: share(s, rep), state_sds)
    comm = getattr(state.opt_state, "comm", None)
    if comm is not None:
        # Compression carry-state (error-feedback residuals / PowerSGD
        # factors) is stacked (world, ...) and lives row-sharded so each
        # worker owns its slice — mirror the Trainer's placement.
        state = state._replace(opt_state=state.opt_state._replace(
            comm=jax.tree_util.tree_map(lambda s: share(s, row), comm)))
    ring = None
    if ring_capacity:
        from ..obs import ringbuf
        ring = (jax.ShapeDtypeStruct((ring_capacity, ringbuf.N_METRICS),
                                     jnp.float32, sharding=rep),
                jax.ShapeDtypeStruct((), jnp.int32, sharding=rep))
    b, w = global_batch, window
    return {
        "state": state,
        "ring": ring,
        "key": jax.ShapeDtypeStruct((2,), jnp.uint32, sharding=rep),
        "images": jax.ShapeDtypeStruct((b, 32, 32, 3), jnp.uint8,
                                       sharding=row),
        "labels": jax.ShapeDtypeStruct((b,), jnp.int32, sharding=row),
        "epoch_images": jax.ShapeDtypeStruct((w, b, 32, 32, 3), jnp.uint8,
                                             sharding=epoch),
        "epoch_labels": jax.ShapeDtypeStruct((w, b), jnp.int32,
                                             sharding=epoch),
        "start": jax.ShapeDtypeStruct((), jnp.int32, sharding=rep),
        "lengths": jax.ShapeDtypeStruct((w,), jnp.int8, sharding=rep),
    }


def _hlo_text(lowered) -> str:
    return lowered.compiler_ir(dialect="hlo").as_hlo_text()


def audit_zoo(*, model: str = "vgg11", global_batch: int = 256,
              window: int = 4, precision: str = "f32",
              strategies: Sequence[str] = ("single", "gather",
                                           "allreduce", "ddp", "overlap",
                                           "compress-bf16", "compress-int8",
                                           "powersgd"),
              paths: Sequence[str] = ("step", "window", "host_window"),
              include_eval: bool = True,
              serve_buckets: Sequence[int] = (),
              serve_precision: Optional[str] = None,
              serve_swap_recert: bool = False,
              num_devices: Optional[int] = None,
              waive: Sequence[str] = (),
              max_constant_bytes: int = DEFAULT_MAX_CONSTANT_BYTES,
              metrics_ring: bool = True,
              collect_hlo: bool = False,
              hbm_budget_bytes: int = 0,
              ) -> AuditResult:
    """Lower and audit the shipped program zoo: the 3 train paths for
    each strategy, the eval window, and (when ``serve_buckets`` is
    non-empty) the serving executable ladder.

    ``metrics_ring`` (default on, matching the Trainer) lowers the
    windowed paths in their ring-carrying form — the programs the Trainer
    actually dispatches — so the donation floor rises by the 2 ring
    buffers and the host-sync rule certifies that the per-step ring
    writes stay pure dynamic-update-slices (no host round-trip inside
    the scanned body).  ``collect_hlo`` keeps every program's lowering
    text on the result (``AuditResult.hlo``) for cost-model attribution.

    Lowering is ABSTRACT end to end — train state shapes come from
    ``jax.eval_shape`` so no parameters are materialized; only the
    serving entries (which reuse :class:`serve.InferenceEngine`)
    initialize real weights.
    """
    import jax

    from ..models import get_model
    from ..obs import ringbuf
    from ..ops import sgd
    from ..parallel import get_strategy, mesh as meshlib
    from ..parallel.bucketing import DEFAULT_BUCKET_BYTES, make_plan
    from ..train import step as steplib

    import jax.numpy as jnp

    compute_dtype = jnp.bfloat16 if precision == "bf16" else None
    init_fn, apply_fn = get_model(model)
    state_sds = jax.eval_shape(
        lambda k: steplib.init_train_state(init_fn, k),
        jax.random.PRNGKey(0))
    params_sds = state_sds.params
    nleaves = len(jax.tree_util.tree_leaves(params_sds))
    param_bytes = sum(l.size * l.dtype.itemsize
                      for l in jax.tree_util.tree_leaves(params_sds))
    nbuckets = make_plan(params_sds, DEFAULT_BUCKET_BYTES).num_buckets
    # Non-gradient collective allowance for the compressed-tier byte
    # ceilings: BN batch-stat pmeans, the int8 shared-scale pmax
    # (f32[nleaves]) and a slack word for loss/count psums.
    bn_bytes = sum(l.size * l.dtype.itemsize
                   for l in jax.tree_util.tree_leaves(state_sds.bn_state))
    aux_bytes = bn_bytes + 4 * nleaves + 1024

    def _compress_ratio(strategy, strat):
        """Analytic wire-byte reduction of a compressed tier on THIS
        model's leaf shapes — exact from the lowering recipe, so the
        contract pins what the program must achieve, not a slogan."""
        if strategy == "compress-bf16":
            return 2.0
        if strategy == "compress-int8":
            return 4.0
        if strategy == "powersgd":
            wire = 0
            for l in jax.tree_util.tree_leaves(params_sds):
                if strat._low_rank(l.shape):
                    m = 1
                    for d in l.shape[:-1]:
                        m *= d
                    wire += 4 * strat.rank * (m + l.shape[-1])  # f32 P + Q
                else:
                    wire += 2 * l.size                          # bf16 path
            return max(1.0, param_bytes / max(1, wire))
        return 1.0

    full_mesh = meshlib.make_mesh(num_devices)
    single_mesh = meshlib.make_mesh(1)
    world = full_mesh.devices.size
    sgd_cfg = sgd.SGDConfig()
    result = AuditResult()
    window_depths: Dict[str, int] = {}

    def contract(name, strategy, w, donates, n_state, ratio):
        return ProgramContract(
            name=name, strategy=strategy, world=w, nleaves=nleaves,
            nbuckets=nbuckets, param_bytes=param_bytes,
            n_state_leaves=n_state, donates_state=donates,
            precision=precision, max_constant_bytes=max_constant_bytes,
            compress_ratio=ratio, aux_bytes=aux_bytes,
            hbm_budget_bytes=hbm_budget_bytes)

    for strategy in strategies:
        mesh = single_mesh if strategy == "single" else full_mesh
        w = mesh.devices.size
        b = max(w, (global_batch // w) * w)
        strat = get_strategy(strategy)
        # Stateful tiers carry (world, ...)-stacked compression state in
        # the optimizer — the abstract state must grow it too.
        st_sds = jax.eval_shape(
            lambda k: steplib.init_train_state(init_fn, k, strat, w),
            jax.random.PRNGKey(0))
        n_state = len(jax.tree_util.tree_leaves(st_sds))
        ratio = _compress_ratio(strategy, strat)
        ring_cap = ringbuf.DEFAULT_CAPACITY if metrics_ring else 0
        sds = _train_sds(mesh, st_sds, b, window, ring_capacity=ring_cap)
        for path in paths:
            name = f"train/{path}/{strategy}"
            ring = metrics_ring and path in ("window", "host_window")
            if path == "step":
                fn = steplib.make_train_step(
                    apply_fn, strat, mesh, sgd_cfg, augment=True,
                    compute_dtype=compute_dtype)
                args = (sds["state"], sds["key"], sds["images"],
                        sds["labels"])
                donates = False
            else:
                fn = steplib.make_train_window(
                    apply_fn, strat, mesh, sgd_cfg,
                    augment=(path == "window"), compute_dtype=compute_dtype,
                    metrics_ring=ring)
                head = ((sds["state"], sds["ring"]) if ring
                        else (sds["state"],))
                args = head + (sds["key"], sds["epoch_images"],
                               sds["epoch_labels"], sds["start"],
                               sds["lengths"])
                donates = True
            # The ring pair is donated alongside the state, so the
            # donation floor rises by its 2 entry buffers.
            n_floor = n_state + (2 if ring else 0)
            text = _hlo_text(fn.lower(*args))
            jaxpr = (jax.make_jaxpr(fn)(*args)
                     if path == "window" else None)
            result.reports.append(audit_program(
                text, contract(name, strategy, w, donates, n_floor, ratio),
                jaxpr, waive=waive))
            if collect_hlo:
                result.hlo[name] = text
            if path == "window":
                window_depths[strategy] = \
                    result.reports[-1].stats["chain_depth"]

    if include_eval:
        sds = _train_sds(full_mesh, state_sds,
                         max(world, (global_batch // world) * world),
                         window)
        ev = steplib.make_eval_window(apply_fn, full_mesh,
                                      compute_dtype=compute_dtype)
        args = (sds["state"], sds["epoch_images"], sds["epoch_labels"])
        text = _hlo_text(ev.lower(*args))
        result.reports.append(audit_program(
            text, contract("eval/window", "eval", world, False,
                           len(jax.tree_util.tree_leaves(state_sds)), 1.0),
            jax.make_jaxpr(ev)(*args), waive=waive))
        if collect_hlo:
            result.hlo["eval/window"] = text

    if serve_buckets:
        result.reports.extend(audit_serving(
            model=model, buckets=serve_buckets,
            precision=serve_precision or precision, waive=waive,
            max_constant_bytes=max_constant_bytes,
            hlo_out=result.hlo if collect_hlo else None,
            swap_recert=serve_swap_recert))

    if world > 1 and len(window_depths) > 1:
        result.ladder, result.ladder_findings = _certify_ladder(
            window_depths, nleaves, nbuckets,
            program="strategy-ladder(train/window)")
        kept = [f for f in result.ladder_findings
                if not _waived(f, waive)]
        result.ladder_findings = kept
    return result


def audit_serving(*, model: str = "vgg11",
                  buckets: Sequence[int] = (1, 8, 32, 128, 256),
                  precision: str = "f32", engine=None,
                  waive: Sequence[str] = (),
                  max_constant_bytes: int = DEFAULT_MAX_CONSTANT_BYTES,
                  hlo_out: Optional[Dict[str, str]] = None,
                  swap_recert: bool = False, swap_seed: int = 1,
                  ) -> List[AuditReport]:
    """Audit the serving executable ladder: one single-device program per
    bucket, required collective-free, precision-certified, constant-lean,
    and fused-ingest certified (``ingest-edge``: uint8 images at the
    program edge, normalize in-program, no float image inputs).
    Pass ``engine`` to audit an already-built :class:`InferenceEngine`
    (the bench serving section does); otherwise one is built without
    staging or caches.  ``hlo_out`` (a dict) collects each rung's
    lowering text under its program name for cost-model attribution.

    ``swap_recert`` re-certifies the ladder under the publish/ hot-swap
    path: differently-seeded weights are installed through
    ``engine.install_weights`` (the same entry point a live swap uses)
    and every rung is re-lowered and re-audited as
    ``serve_swap/b{bucket}/{precision}`` — the baked-constants rule on
    the POST-swap program set proves the executables stay weight-
    agnostic across installs (weights remain runtime arguments, never
    folded), which is what makes the zero-recompile swap sound."""
    if engine is None:
        from ..serve import InferenceEngine
        engine = InferenceEngine(model, buckets=tuple(buckets),
                                 precisions=(precision,),
                                 use_staging=False,
                                 enable_compilation_cache=False)
    reports = []

    def _audit_rungs(prefix: str) -> None:
        for b in engine.buckets:
            name = f"{prefix}/b{b}/{precision}"
            c = ProgramContract(
                name=name, strategy=None, world=1,
                precision=precision, max_constant_bytes=max_constant_bytes,
                u8_edge=True)
            text = engine.lowered_hlo(b, precision)
            reports.append(audit_program(text, c, waive=waive))
            if hlo_out is not None:
                hlo_out[name] = text

    _audit_rungs("serve")
    if swap_recert:
        import jax
        from ..models import get_model
        from ..train.step import init_train_state
        init_fn, _ = get_model(engine.model_name)
        alt = init_train_state(init_fn, jax.random.PRNGKey(swap_seed))
        engine.install_weights(alt.params, alt.bn_state,
                               engine.weights_version + 1)
        _audit_rungs("serve_swap")
    return reports


def record_audit(telemetry, result: AuditResult) -> None:
    """Attach the audit summary to the run manifest.  The disabled
    recorder path allocates and touches NOTHING (exploding-recorder
    pinned in tests/test_analysis.py)."""
    if not getattr(telemetry, "enabled", False):
        return
    telemetry.update_manifest({"audit": result.summary()})


def zoo_attribution(result: AuditResult) -> Dict:
    """Static cost-model attribution over an audited zoo's lowerings
    (requires ``audit_zoo(..., collect_hlo=True)``): per-program analytic
    FLOPs / HBM / wire bytes -> roofline attribution, plus the
    overlap-vs-ddp exposed-communication bound when both tiers are
    present.  Pure static analysis — no dispatch, no devices."""
    from . import costmodel
    from ..obs import attribution as attrlib
    if not result.hlo:
        raise ValueError("audit result carries no HLO text; re-run "
                         "audit_zoo(..., collect_hlo=True)")
    reports = {name: costmodel.cost_report(text, name)
               for name, text in result.hlo.items()}
    programs = {name: attrlib.attribute(
                    rep, mem_report=memlife.mem_report(result.hlo[name],
                                                       name))
                for name, rep in reports.items()}
    out: Dict = {"programs": programs}
    ov, dd = (reports.get("train/window/overlap"),
              reports.get("train/window/ddp"))
    if ov is not None and dd is not None:
        out["overlap_vs_ddp"] = attrlib.overlap_vs_ddp(ov, dd)
    return out


def record_attribution(telemetry, attribution: Dict) -> None:
    """Attach a :func:`zoo_attribution` record to the run manifest; the
    disabled recorder path allocates and touches NOTHING."""
    if not getattr(telemetry, "enabled", False):
        return
    telemetry.update_manifest({"attribution": attribution})
