"""Whole-package lock-order deadlock detector (round 13).

Five threaded subsystems now interleave through six-plus class locks
(scheduler, batcher, watcher, telemetry, alerts, coordinator), and the
dangerous paths are CROSS-OBJECT: the watcher calls
``scheduler.request_install`` while holding its own lock, alert
callbacks re-enter telemetry, batch admission consults the service
model under the scheduler condition.  No test reliably provokes an
ABBA interleaving; this pass certifies its absence statically.

The analyzer builds a lock-acquisition graph over every class in the
package that owns a ``threading.Lock/RLock/Condition``:

* **nodes** are class locks, named ``ClassName.lockattr``;
* **edges** ``A -> B`` mean "somewhere, B is acquired while A is held".

Held regions are ``with self.<lock>:`` bodies, the statements following
a conditional ``self.<lock>.acquire(...)`` in the same block (the
watcher's non-blocking poll idiom), and the whole body of any
``*_locked``-suffixed method (the caller-holds contract).  Lock
effects propagate transitively: through same-class self-calls
(``observe -> _outcome -> _fire``) and through cross-object method
calls whose name resolves UNIQUELY among lock-owning classes
(``r.scheduler.request_install`` -> ``SLOScheduler``, ``tel.gauge`` ->
``Telemetry``).  Ambiguous names (``observe`` lives on both
``ServiceModel`` and ``AlertEngine``) are skipped rather than guessed —
the detector under-approximates edges, never invents them.

Verified properties, each a LintFinding on failure:

* ``lock-cycle`` — the graph must be acyclic;
* ``lock-order-violation`` / ``lock-order-undeclared`` — every edge
  must descend the declared partial order ``LOCK_ORDER`` below (the
  certified order BASELINE.md records);
* ``lock-caller-holds`` — a ``*_locked`` method may only be called with
  its class lock held (from a held region or another ``*_locked``
  method of the same class).  This is what makes the lint's
  ``*_locked`` exemption sound: the lint trusts the suffix, this pass
  verifies every call site of the suffix;
* ``lock-cross-locked-call`` — ``*_locked`` methods are private to
  their class; calling one on another object cannot be proven held.

Known blind spots, on purpose: callbacks stored in attributes
(``Watchdog._on_timeout``) and bare-function indirection
(``predict_s=self.svc.predict`` passed as a value) are invisible —
the visible call path through ``_retry_hint_ms_locked`` pins the same
edge, and the partial order makes any hidden edge in the same
direction safe by construction.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, Set, Tuple

from .pylint_rules import LintFinding, _call_name, _lock_attrs, _self_attr

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_ROOT = os.path.dirname(_PKG_ROOT)

#: The certified partial order: an edge A -> B (B acquired while A is
#: held) is legal iff A appears STRICTLY BEFORE B here.  Outermost
#: (coarsest, longest-held) locks first; Telemetry is last because every
#: subsystem may emit telemetry from inside its own critical section and
#: telemetry must therefore never call back out while holding its lock.
LOCK_ORDER: Tuple[str, ...] = (
    "WeightWatcher._lock",        # publish poll/install; calls into sched
    "AlertEngine._lock",          # rule evaluation; emits telemetry
    "ElasticCoordinator._lock",
    "Watchdog._lock",
    "ChaosPlan._lock",
    "ReplicaRouter._lock",
    "ServingFrontend._lock",
    "FrontendClient._lock",
    "SLOScheduler._cond",         # admission; consults the service model
    "MicroBatcher._cond",         # queueing; emits telemetry
    "ServiceModel._lock",
    "Telemetry._lock",            # leaf: never calls out while held
)

_LOCK_METHODS = frozenset({"acquire", "release", "wait", "wait_for",
                           "notify", "notify_all", "locked"})
_BLOCK_FIELDS = ("body", "orelse", "finalbody")


@dataclass(frozen=True)
class CallSite:
    """One call observed inside a method, with the self-locks held."""

    held: FrozenSet[str]          # lock ATTRS of the owning class held
    recv: str                     # "self" | "other"
    name: str                     # method name called
    line: int


@dataclass
class MethodSummary:
    cls: str
    name: str
    path: str
    locks: FrozenSet[str]         # the owning class's lock attrs
    acquires: List[Tuple[FrozenSet[str], str, int]] = field(
        default_factory=list)     # (held-before, lock attr, line)
    calls: List[CallSite] = field(default_factory=list)
    locked_suffix: bool = False   # name ends with _locked

    @property
    def node_prefix(self) -> str:
        return self.cls + "."


@dataclass
class LockGraph:
    nodes: Set[str] = field(default_factory=set)
    #: (src, dst) -> evidence [(path, line, description)]
    edges: Dict[Tuple[str, str], List[Tuple[str, int, str]]] = field(
        default_factory=dict)
    findings: List[LintFinding] = field(default_factory=list)

    def add_edge(self, src: str, dst: str, path: str, line: int,
                 why: str) -> None:
        if src == dst:
            return                # RLock re-entry / same-lock nesting
        self.edges.setdefault((src, dst), []).append((path, line, why))


def _expr_calls(stmt: ast.stmt) -> Iterable[ast.Call]:
    """Call nodes in the statement's OWN expressions — not in nested
    statement blocks (those are visited with their own held set)."""
    for fname, value in ast.iter_fields(stmt):
        if fname in _BLOCK_FIELDS or fname == "handlers":
            continue
        nodes = value if isinstance(value, list) else [value]
        for n in nodes:
            if isinstance(n, ast.AST):
                for sub in ast.walk(n):
                    if isinstance(sub, ast.Call):
                        yield sub


def _summarize_method(cls: ast.ClassDef, method: ast.FunctionDef,
                      locks: Set[str], path: str) -> MethodSummary:
    summ = MethodSummary(cls=cls.name, name=method.name, path=path,
                         locks=frozenset(locks),
                         locked_suffix=method.name.endswith("_locked"))
    # A *_locked method's whole body runs with the class lock held by
    # contract; lock-caller-holds (below) verifies every call site.
    base_held = frozenset(locks) if summ.locked_suffix else frozenset()

    def visit_block(stmts: Sequence[ast.stmt], held: FrozenSet[str]) -> None:
        for stmt in stmts:
            acquired_here: Set[str] = set()
            for call in _expr_calls(stmt):
                name = _call_name(call)
                if name is None:
                    continue
                if (name in _LOCK_METHODS
                        and isinstance(call.func, ast.Attribute)):
                    attr = _self_attr(call.func.value)
                    if attr in locks and name == "acquire":
                        summ.acquires.append((held, attr, call.lineno))
                        acquired_here.add(attr)
                    continue      # wait/notify/release: not call edges
                recv = "other"
                if isinstance(call.func, ast.Attribute) and \
                        isinstance(call.func.value, ast.Name) and \
                        call.func.value.id == "self":
                    recv = "self"
                elif isinstance(call.func, ast.Name):
                    continue      # bare functions: module-level, no class
                summ.calls.append(CallSite(held, recv, name, call.lineno))
            if isinstance(stmt, ast.With):
                inner = set(held)
                for item in stmt.items:
                    attr = _self_attr(item.context_expr)
                    if attr in locks:
                        summ.acquires.append((held, attr, stmt.lineno))
                        inner.add(attr)
                visit_block(stmt.body, frozenset(inner))
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                pass              # nested defs run later, on their own terms
            else:
                for fname in _BLOCK_FIELDS:
                    sub = getattr(stmt, fname, None)
                    if sub:
                        visit_block(sub, held)
                for handler in getattr(stmt, "handlers", ()):
                    visit_block(handler.body, held)
            if acquired_here:
                # Conditional-acquire idiom: the rest of this block only
                # runs once the acquire succeeded (the failure arm
                # returns), so treat it as held from here on.
                held = frozenset(held | acquired_here)
    visit_block(method.body, base_held)
    return summ


def build_graph(sources: Dict[str, str]) -> LockGraph:
    """Build the lock graph over {path: source}."""
    graph = LockGraph()
    methods: Dict[Tuple[str, str], MethodSummary] = {}  # (cls, name) ->
    by_name: Dict[str, List[str]] = {}                  # method -> [cls]
    class_locks: Dict[str, FrozenSet[str]] = {}

    for path in sorted(sources):
        tree = ast.parse(sources[path])
        for cls in ast.walk(tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            locks = _lock_attrs(cls)
            if not locks:
                continue
            class_locks[cls.name] = frozenset(locks)
            for lk in sorted(locks):
                graph.nodes.add(f"{cls.name}.{lk}")
            for item in cls.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    methods[(cls.name, item.name)] = _summarize_method(
                        cls, item, locks, path)
                    by_name.setdefault(item.name, []).append(cls.name)

    def resolve(site: CallSite, cls: str) -> Optional[Tuple[str, str]]:
        """(class, method) a call site refers to, or None if unknown or
        ambiguous among lock-owning classes."""
        if site.recv == "self":
            return (cls, site.name) if (cls, site.name) in methods else None
        owners = by_name.get(site.name, [])
        return (owners[0], site.name) if len(owners) == 1 else None

    # -- transitive lock effects: method -> nodes it may acquire --------
    effects: Dict[Tuple[str, str], Set[str]] = {
        key: {f"{key[0]}.{lk}" for _, lk, _ in m.acquires}
        for key, m in methods.items()}
    changed = True
    while changed:
        changed = False
        for key, m in methods.items():
            acc = effects[key]
            before = len(acc)
            for site in m.calls:
                target = resolve(site, key[0])
                if target is not None:
                    acc |= effects[target]
            if len(acc) != before:
                changed = True

    # -- edges ----------------------------------------------------------
    for (cls, mname), m in methods.items():
        for held, lk, line in m.acquires:
            for h in held:
                graph.add_edge(f"{cls}.{h}", f"{cls}.{lk}", m.path, line,
                               f"{cls}.{mname} acquires self.{lk} while "
                               f"holding self.{h}")
        for site in m.calls:
            if not site.held:
                continue
            target = resolve(site, cls)
            if target is None:
                continue
            for node in sorted(effects[target]):
                for h in site.held:
                    graph.add_edge(
                        f"{cls}.{h}", node, m.path, site.line,
                        f"{cls}.{mname} calls {target[0]}.{site.name}() "
                        f"while holding self.{h}")

    # -- *_locked caller-holds verification -----------------------------
    for (cls, mname), m in methods.items():
        for site in m.calls:
            if not site.name.endswith("_locked"):
                continue
            if site.recv != "self":
                owners = by_name.get(site.name, [])
                if owners and owners != [cls]:
                    graph.findings.append(LintFinding(
                        "lock-cross-locked-call", m.path, site.line,
                        f"{cls}.{mname} calls {site.name}() on another "
                        f"object — *_locked methods are private to their "
                        f"class's critical sections"))
                continue
            if (cls, site.name) not in methods:
                continue
            if not site.held and not m.locked_suffix:
                graph.findings.append(LintFinding(
                    "lock-caller-holds", m.path, site.line,
                    f"{cls}.{mname} calls self.{site.name}() without "
                    f"holding {'/'.join(sorted(m.locks))} — the _locked "
                    f"suffix promises the caller holds the lock"))
    return graph


def check_graph(graph: LockGraph,
                order: Sequence[str] = LOCK_ORDER) -> List[LintFinding]:
    """Partial-order + acyclicity findings for a built graph."""
    findings = list(graph.findings)
    rank = {name: i for i, name in enumerate(order)}
    for (src, dst), evidence in sorted(graph.edges.items()):
        path, line, why = evidence[0]
        if src not in rank or dst not in rank:
            missing = ", ".join(n for n in (src, dst) if n not in rank)
            findings.append(LintFinding(
                "lock-order-undeclared", path, line,
                f"edge {src} -> {dst} involves lock(s) not in the "
                f"declared LOCK_ORDER ({missing}) — declare the rank "
                f"in analysis/lockgraph.py ({why})"))
        elif rank[src] >= rank[dst]:
            findings.append(LintFinding(
                "lock-order-violation", path, line,
                f"edge {src} -> {dst} ascends the declared partial "
                f"order — inverting it can deadlock against the "
                f"declared direction ({why})"))
    for cycle in _cycles(graph):
        first = graph.edges[(cycle[0], cycle[1])][0]
        findings.append(LintFinding(
            "lock-cycle", first[0], first[1],
            "lock cycle: " + " -> ".join(cycle)))
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def _cycles(graph: LockGraph) -> List[List[str]]:
    """Elementary cycles via DFS (the graph has ~a dozen nodes)."""
    adj: Dict[str, List[str]] = {}
    for src, dst in graph.edges:
        adj.setdefault(src, []).append(dst)
    cycles: List[List[str]] = []
    seen_keys: Set[FrozenSet[str]] = set()

    def dfs(node: str, path: List[str], on_path: Set[str]) -> None:
        for nxt in sorted(adj.get(node, [])):
            if nxt in on_path:
                cyc = path[path.index(nxt):] + [nxt]
                key = frozenset(cyc)
                if key not in seen_keys:
                    seen_keys.add(key)
                    cycles.append(cyc)
                continue
            dfs(nxt, path + [nxt], on_path | {nxt})

    for start in sorted(adj):
        dfs(start, [start], {start})
    return cycles


def certified_order(graph: LockGraph,
                    order: Sequence[str] = LOCK_ORDER) -> List[str]:
    """The declared order restricted to locks that exist in the graph —
    what BASELINE.md records as the certified partial order."""
    return [n for n in order if n in graph.nodes]


def graph_summary(graph: LockGraph) -> dict:
    """JSON-ready description (BASELINE.md / --verify-static)."""
    return {
        "nodes": sorted(graph.nodes),
        "edges": [{"src": s, "dst": d, "sites": len(ev)}
                  for (s, d), ev in sorted(graph.edges.items())],
        "certified_order": certified_order(graph),
    }


def _package_sources(repo_root: str = _REPO_ROOT) -> Dict[str, str]:
    sources: Dict[str, str] = {}
    pkg = os.path.join(repo_root, "cs744_ddp_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git")]
        for name in sorted(filenames):
            if name.endswith(".py"):
                path = os.path.join(dirpath, name)
                with open(path, encoding="utf-8") as fh:
                    sources[path] = fh.read()
    return sources


def build_repo_graph(repo_root: str = _REPO_ROOT) -> LockGraph:
    return build_graph(_package_sources(repo_root))


def check_locks(repo_root: str = _REPO_ROOT) -> List[LintFinding]:
    """The whole-package run: [] = lock graph certified."""
    return check_graph(build_repo_graph(repo_root))


def check_source(source: str, path: str = "<source>",
                 order: Sequence[str] = LOCK_ORDER) -> List[LintFinding]:
    """Single-source entry point for fixtures/tests."""
    return check_graph(build_graph({path: source}), order)
