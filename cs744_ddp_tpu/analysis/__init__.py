"""Static analysis of compiled programs (HLO graph IR, audits, lint).

Three layers, all import-light (jax only where a rule needs a jaxpr):

- ``hlo_ir``     — tokenizer + parser for HLO text (both the optimized
                   ``%``-sigil print and the bare pre-optimization print)
                   into a module/computation/instruction graph IR.
- ``stats``      — the collective-accounting API (``collective_stats``,
                   ``collective_chain_depth``, ``bytes_of_type``) rebuilt
                   on the IR; ``utils/hlo_stats.py`` is now a thin adapter
                   over this module and its regex implementation survives
                   only as ``legacy_*`` differential-test oracles.
- ``audit``      — a rule engine certifying each shipped program's cost
                   shape (collective contract per strategy, dtype leaks,
                   donation misses, host syncs in loop bodies, oversized
                   baked constants) wired into ``cli.py --audit``, bench's
                   ``audit`` section and the telemetry manifest.
- ``pylint_rules`` — AST lint for repo invariants the runtime can't see
                   (un-fenced timing, jnp on producer threads, lock
                   ownership); ``tools/lint_graft.py`` is the CLI.
- ``lockgraph``  — whole-package lock-order deadlock detector: builds the
                   cross-class lock-acquisition graph, certifies it
                   acyclic against the declared ``LOCK_ORDER`` partial
                   order, and verifies every ``*_locked`` call site
                   (round 13).
- ``wire_schema`` — wire-protocol schema conformance: every struct
                   format/TLV tag in the codec sources against the
                   declarative ``serve/wire.py`` table, encoder/decoder
                   symmetry, and total extension parsing (round 13).
- ``dispatch``   — static host-round-trip certifier: closed-form
                   per-epoch round-trip bounds from the lowered
                   programs' scan structure, pinned EXACTLY against the
                   runtime ``host_round_trips`` counter (round 13).

``tools/lint_graft.py`` and ``cli.py --verify-static`` run the three
whole-program analyzers together;
``tests/test_analysis.py::test_repo_static_verification`` is the tier-1
CI gate.
"""

from .stats import bytes_of_type, collective_chain_depth, collective_stats

__all__ = ["bytes_of_type", "collective_chain_depth", "collective_stats"]
