"""Closed-form feasibility planner for the K-epoch mega-program.

ROADMAP item 3 wants to fuse K whole epochs — train windows, on-device
eval, the metrics ring — into ONE dispatched program, collapsing the
per-epoch host round-trips (:func:`dispatch.epoch_round_trip_bound`)
to the O(1) of :func:`dispatch.mega_round_trip_bound`.  The blocking
question is sizing: every staged epoch parks its shuffled u8 batches
and its ring rows in HBM for the whole dispatch, so K is bounded by
the chip's 16 GiB.  This module answers ``max_feasible_K`` in closed
form, composing three certified inputs:

- the per-window static memory certificate (:func:`memlife.mem_report`
  over the lowered train window — state bytes and the transient peak
  the window's compute needs on top of them);
- the ring carry growth model (one ``(N_METRICS,)`` f32 row per step,
  :mod:`obs.ringbuf` — a K-epoch ring must hold every row until the
  single drain, so it grows 16 B per step instead of wrapping at
  ``DEFAULT_CAPACITY``);
- the staging slab: epochs are dispatched at WINDOW granularity, so a
  K-epoch program stages ``ceil(nbatches/window) * window`` per-chip
  batches per epoch (window padding included — a bigger window pads
  more and can only shrink K).

All byte models are per CHIP: the slab and labels are data-sharded
(``global_batch / world`` rows per chip), the state and ring are
replicated.  The HBM budget defaults to the single-sourced
:data:`costmodel.V5E_HBM_CAPACITY_BYTES`.

``plan_k_epochs`` is pure arithmetic (jax-free, unit-pinned against
hand-computed ring + state bytes in tests/test_memlife.py);
``max_feasible_K`` lowers the real train window via the audit
machinery to obtain the state/transient bytes, then delegates.  The
result is the go/no-go artifact the mega-program PR builds against:
its entry criterion is ``max_feasible_K(...) >= K`` for the K it
proposes to fuse.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from . import costmodel, dispatch, memlife
from ..obs import ringbuf

#: CIFAR-10 sample footprint on the wire/stage path: u8 HWC image + i32
#: label.  The serving/train ingest contract keeps images uint8 end to
#: end, so the staged slab is 1 byte/px.
IMG_BYTES = 32 * 32 * 3
LABEL_BYTES = 4

#: One ring row per scanned step: N_METRICS f32 columns.
RING_ROW_BYTES = 4 * ringbuf.N_METRICS
#: The i32 write counter carried beside the ring rows.
RING_COUNTER_BYTES = 4

#: CIFAR-10 train-split size, the default epoch length numerator.
TRAIN_EXAMPLES = 50_000


@dataclass
class KEpochPlan:
    """Feasibility certificate for fusing K epochs into one dispatch."""

    model: str
    world: int
    window: int
    global_batch: int
    nbatches: int                    # full batches per epoch
    hbm_budget_bytes: int
    state_bytes: int                 # donated train state, replicated
    transient_bytes: int             # window-program compute peak
    fixed_bytes: int                 # K-independent residency
    slab_bytes_per_epoch: int        # staged u8 images + labels, per chip
    ring_bytes_per_epoch: int        # metric rows appended per epoch
    per_epoch_bytes: int
    max_k: int
    windowed_round_trips_per_epoch: int
    mega_round_trips: int            # for max_k epochs fused into one
    notes: List[str] = field(default_factory=list)

    @property
    def round_trips_saved(self) -> int:
        """Host round-trips the max-K mega-program erases vs dispatching
        the same epochs down the windowed path."""
        if self.max_k <= 0:
            return 0
        return (self.max_k * self.windowed_round_trips_per_epoch
                - self.mega_round_trips)

    def to_dict(self) -> Dict:
        return {
            "model": self.model,
            "world": self.world,
            "window": self.window,
            "global_batch": self.global_batch,
            "nbatches": self.nbatches,
            "hbm_budget_mib": round(self.hbm_budget_bytes / 2**20, 1),
            "state_mib": round(self.state_bytes / 2**20, 3),
            "transient_mib": round(self.transient_bytes / 2**20, 3),
            "fixed_mib": round(self.fixed_bytes / 2**20, 3),
            "slab_mib_per_epoch": round(
                self.slab_bytes_per_epoch / 2**20, 3),
            "ring_kib_per_epoch": round(
                self.ring_bytes_per_epoch / 2**10, 3),
            "max_k": self.max_k,
            "windowed_round_trips_per_epoch":
                self.windowed_round_trips_per_epoch,
            "mega_round_trips": self.mega_round_trips,
            "round_trips_saved": self.round_trips_saved,
            "notes": list(self.notes),
        }


def ring_bytes_for_steps(steps: int) -> int:
    """Ring rows for ``steps`` scanned steps with no wraparound — the
    K-epoch ring must keep every row until its single drain."""
    return steps * RING_ROW_BYTES


def slab_bytes_per_epoch(nbatches: int, window: int, global_batch: int,
                         world: int) -> int:
    """Per-chip staged bytes for one epoch: windows are cut at WINDOW
    boundaries, so the stage pads to ``ceil(nbatches/window) * window``
    batches of data-sharded u8 images + i32 labels."""
    if nbatches <= 0 or window <= 0 or world <= 0:
        raise ValueError(f"bad slab query: nbatches={nbatches} "
                         f"window={window} world={world}")
    padded_steps = math.ceil(nbatches / window) * window
    per_chip_batch = max(1, global_batch // world)
    return padded_steps * per_chip_batch * (IMG_BYTES + LABEL_BYTES)


def plan_k_epochs(*, model: str = "vgg11", world: int = 8, window: int = 4,
                  global_batch: int = 256, nbatches: Optional[int] = None,
                  state_bytes: int, transient_bytes: int = 0,
                  hbm_budget_bytes: Optional[int] = None) -> KEpochPlan:
    """The closed form.  K-independent residency = state + the window
    transient peak + the ring counter; each staged epoch adds its slab
    and its ring rows.  ``max_k`` is the largest K whose total fits the
    budget (0 when even the fixed residency does not fit)."""
    budget = (costmodel.V5E_HBM_CAPACITY_BYTES
              if hbm_budget_bytes is None else hbm_budget_bytes)
    if nbatches is None:
        nbatches = max(1, TRAIN_EXAMPLES // global_batch)
    fixed = state_bytes + transient_bytes + RING_COUNTER_BYTES
    slab = slab_bytes_per_epoch(nbatches, window, global_batch, world)
    ring = ring_bytes_for_steps(nbatches)
    per_epoch = slab + ring
    max_k = max(0, (budget - fixed) // per_epoch) if per_epoch else 0
    plan = KEpochPlan(
        model=model, world=world, window=window,
        global_batch=global_batch, nbatches=nbatches,
        hbm_budget_bytes=budget, state_bytes=state_bytes,
        transient_bytes=transient_bytes, fixed_bytes=fixed,
        slab_bytes_per_epoch=slab, ring_bytes_per_epoch=ring,
        per_epoch_bytes=per_epoch, max_k=int(max_k),
        windowed_round_trips_per_epoch=dispatch.epoch_round_trip_bound(
            "window", nbatches, window, include_eval=True),
        mega_round_trips=dispatch.mega_round_trip_bound(
            int(max_k), include_eval=True))
    if max_k <= 0:
        plan.notes.append(
            f"infeasible: fixed residency {fixed} B + one epoch "
            f"{per_epoch} B exceed the {budget} B budget")
    return plan


def lower_window(model: str = "vgg11", *, world: int = 8,
                 window: int = 4, global_batch: int = 256,
                 strategy: str = "ddp", metrics_ring: bool = True):
    """Lower THE train window (the same recipe the audit zoo uses);
    returns ``(lowered, name)`` so callers can take the HLO text for the
    static certifier AND ``.compile()`` it for the differential check.
    Requires jax; lowering is abstract (eval_shape), no parameters
    materialize."""
    import jax

    from . import audit
    from ..models import get_model
    from ..ops import sgd
    from ..parallel import get_strategy, mesh as meshlib
    from ..train import step as steplib

    mesh = meshlib.make_mesh(world)
    w = mesh.devices.size
    b = max(w, (global_batch // w) * w)
    strat = get_strategy(strategy if w > 1 else "single")
    init_fn, apply_fn = get_model(model)
    st_sds = jax.eval_shape(
        lambda k: steplib.init_train_state(init_fn, k, strat, w),
        jax.random.PRNGKey(0))
    ring_cap = ringbuf.DEFAULT_CAPACITY if metrics_ring else 0
    sds = audit._train_sds(mesh, st_sds, b, window, ring_capacity=ring_cap)
    fn = steplib.make_train_window(
        apply_fn, strat, mesh, sgd.SGDConfig(), augment=True,
        metrics_ring=metrics_ring)
    head = (sds["state"], sds["ring"]) if metrics_ring else (sds["state"],)
    args = head + (sds["key"], sds["epoch_images"], sds["epoch_labels"],
                   sds["start"], sds["lengths"])
    return fn.lower(*args), f"train/window/{strategy}@w{w}/{model}"


def window_mem_report(model: str = "vgg11", *, world: int = 8,
                      window: int = 4, global_batch: int = 256,
                      strategy: str = "ddp",
                      metrics_ring: bool = True) -> memlife.MemReport:
    """Lower the train window and run the liveness certifier over it —
    the per-window MemReport the planner composes."""
    from . import audit

    lowered, name = lower_window(
        model, world=world, window=window, global_batch=global_batch,
        strategy=strategy, metrics_ring=metrics_ring)
    return memlife.mem_report(audit._hlo_text(lowered), name)


def state_bytes_for(model: str, *, world: int = 8,
                    strategy: str = "ddp") -> int:
    """Donated train-state bytes (params + momentum + BN + step), from
    ``jax.eval_shape`` — the replicated, K-independent carry."""
    import jax

    from ..models import get_model
    from ..parallel import get_strategy
    from ..train import step as steplib

    strat = get_strategy(strategy if world > 1 else "single")
    init_fn, _ = get_model(model)
    st_sds = jax.eval_shape(
        lambda k: steplib.init_train_state(init_fn, k, strat, world),
        jax.random.PRNGKey(0))
    return sum(l.size * l.dtype.itemsize
               for l in jax.tree_util.tree_leaves(st_sds))


def max_feasible_K(model: str = "vgg11", world: int = 8, window: int = 4,
                   hbm_budget_bytes: Optional[int] = None, *,
                   global_batch: int = 256, nbatches: Optional[int] = None,
                   strategy: str = "ddp",
                   window_report: Optional[memlife.MemReport] = None,
                   ) -> int:
    """The go/no-go number: the largest K epochs of ``model`` at
    ``world`` chips and ``window``-step windows that fit one chip's HBM
    budget.  Lowering the window (for the transient peak) is skipped
    when the caller supplies ``window_report``."""
    plan = plan_feasibility(
        model, world, window, hbm_budget_bytes,
        global_batch=global_batch, nbatches=nbatches, strategy=strategy,
        window_report=window_report)
    return plan.max_k


def plan_feasibility(model: str = "vgg11", world: int = 8, window: int = 4,
                     hbm_budget_bytes: Optional[int] = None, *,
                     global_batch: int = 256,
                     nbatches: Optional[int] = None, strategy: str = "ddp",
                     window_report: Optional[memlife.MemReport] = None,
                     ) -> KEpochPlan:
    """Full :class:`KEpochPlan` behind :func:`max_feasible_K`."""
    if window_report is None:
        window_report = window_mem_report(
            model, world=world, window=window, global_batch=global_batch,
            strategy=strategy)
    plan = plan_k_epochs(
        model=model, world=world, window=window, global_batch=global_batch,
        nbatches=nbatches,
        state_bytes=state_bytes_for(model, world=world, strategy=strategy),
        transient_bytes=window_report.transient_peak_bytes,
        hbm_budget_bytes=hbm_budget_bytes)
    plan.notes.append(
        f"transient peak from {window_report.name}: "
        f"{window_report.transient_peak_bytes} B (static, pre-SPMD "
        f"global shapes — conservative per chip)")
    return plan
