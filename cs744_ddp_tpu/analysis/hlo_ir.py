"""Tokenizer + parser for HLO text producing a small graph IR.

XLA prints a module in two dialects and this parser accepts both:

- the OPTIMIZED print — ``%``-sigils on every name, operands carry their
  types (``f32[64,10]{1,0} %fusion.1``), layouts/tiling in braces
  (``{1,0:T(8,128)S(1)}``), ``metadata={...}`` trailers;
- the PRE-OPTIMIZATION print (``lowered.compiler_ir(dialect="hlo")``) —
  bare names, untyped operands, no layouts.

The previous approach (``utils/hlo_stats.py``) ran regexes over raw lines
and was print-format-sensitive: a quoted brace inside ``source_file`` or a
``metadata op_name`` colliding with an instruction name historically
poisoned the dependency graph, each patched with one more regex.  Here the
text is scanned character-wise with bracket- and string-awareness, so
attributes, operands and called computations are STRUCTURAL fields, not
token soup; downstream analyses (``analysis/stats.py``, ``analysis/audit``)
never see a string literal or a metadata block unless they ask for it.

The IR is deliberately small: a :class:`Module` holds header attributes
(``buffer_donor``/``input_output_alias`` feed the donation audit) and
ordered :class:`Computation`\\ s; each computation holds ordered
:class:`Instruction`\\ s with opcode, result type, operand names, attribute
list and called-computation names.  ``Module.to_text()`` reprints the
parse, and ``parse(to_text(parse(x)))`` is structurally identical —
pinned by tests/test_analysis.py's round-trip test.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

# Attribute keys whose values name other computations in the module.
CALLED_ATTRS = ("to_apply", "body", "condition", "calls",
                "branch_computations", "called_computations",
                "computations")

# Canonical element widths for HLO dtypes.  analysis/stats.py aliases
# this table — one copy, so the byte accounting of the collective audit
# and the liveness certifier (analysis/memlife) can never disagree.
DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
}

_IDENT_RE = re.compile(r"[%A-Za-z_][\w.\-]*")
_NAME_AT_END_RE = re.compile(r"(%?[\w.\-]+)\s*$")
_OPCODE_RE = re.compile(r"[a-z][\w\-]*")
# Computation header: `%name (params) -> type {` (optimized) or the bare
# pre-optimization `name {`; `ENTRY`-prefixed for the entry computation.
_COMP_HEAD_RE = re.compile(
    r"^\s*(?:ENTRY\s+)?(?P<name>%?[\w.\-]+)\s*(?:\([^)]*\))?"
    r"\s*(?:->\s*[^{]*)?\{\s*$")
_INSTR_RE = re.compile(
    r"^\s*(?P<root>ROOT\s+)?(?P<name>%?[\w.\-]+)\s*=\s*(?P<rhs>.+)$")

_OPEN = {"(": ")", "[": "]", "{": "}"}
_CLOSE = {")", "]", "}"}


class HloParseError(ValueError):
    pass


def _scan_string(s: str, i: int) -> int:
    """``s[i]`` is ``\"``; return the index just past the closing quote,
    honouring backslash escapes."""
    i += 1
    while i < len(s):
        c = s[i]
        if c == "\\":
            i += 2
            continue
        if c == '"':
            return i + 1
        i += 1
    return len(s)  # unterminated: tolerate, consume to end


def _scan_balanced(s: str, i: int) -> int:
    """``s[i]`` is an opening bracket; return the index just past its
    matching close, skipping strings and nested brackets of any kind
    (layout annotations like ``{1,0:T(8,128)S(1)}`` nest parens in
    braces)."""
    depth = 0
    while i < len(s):
        c = s[i]
        if c == '"':
            i = _scan_string(s, i)
            continue
        if c in _OPEN:
            depth += 1
        elif c in _CLOSE:
            depth -= 1
            if depth == 0:
                return i + 1
        i += 1
    return len(s)


def split_top(s: str, sep: str = ",") -> List[str]:
    """Split ``s`` at top-level occurrences of ``sep`` (outside every
    bracket pair and string literal)."""
    parts: List[str] = []
    buf_start = 0
    i = 0
    while i < len(s):
        c = s[i]
        if c == '"':
            i = _scan_string(s, i)
            continue
        if c in _OPEN:
            i = _scan_balanced(s, i)
            continue
        if c == sep:
            parts.append(s[buf_start:i])
            buf_start = i + 1
        i += 1
    parts.append(s[buf_start:])
    return parts


@dataclass
class Instruction:
    name: str                              # sigil-stripped
    opcode: str
    result_type: str                       # "" when the print omits it
    operands: Tuple[str, ...]              # referenced value names, stripped
    operand_raw: Tuple[str, ...]           # operand text as printed
    attrs: Tuple[Tuple[str, str], ...]     # ordered (key, raw value)
    is_root: bool = False
    sigil: bool = False                    # name printed with '%'
    line_no: int = 0

    def attr(self, key: str) -> Optional[str]:
        for k, v in self.attrs:
            if k == key:
                return v
        return None

    @property
    def called(self) -> Tuple[str, ...]:
        """Names of computations this instruction invokes (while bodies,
        reducers, fusion/call targets, conditional branches)."""
        out: List[str] = []
        for key in CALLED_ATTRS:
            raw = self.attr(key)
            if not raw:
                continue
            for tok in _IDENT_RE.findall(raw):
                out.append(tok.lstrip("%"))
        return tuple(out)

    def to_text(self) -> str:
        head = "ROOT " if self.is_root else ""
        name = ("%" + self.name) if self.sigil else self.name
        rtype = (self.result_type + " ") if self.result_type else ""
        ops = ", ".join(self.operand_raw)
        attrs = "".join(
            f", {k}={v}" if v is not None else f", {k}"
            for k, v in self.attrs)
        return f"{head}{name} = {rtype}{self.opcode}({ops}){attrs}"


@dataclass
class Computation:
    name: str                              # sigil-stripped
    header: str                            # header line as printed (sans indent)
    is_entry: bool = False
    instructions: Dict[str, Instruction] = field(default_factory=dict)

    @property
    def root(self) -> Optional[Instruction]:
        root = None
        for ins in self.instructions.values():
            if ins.is_root:
                return ins
            root = ins                     # fall back to the last def
        return root

    def to_text(self) -> str:
        lines = [("ENTRY " if self.is_entry else "") + self.header]
        for ins in self.instructions.values():
            lines.append("  " + ins.to_text())
        lines.append("}")
        return "\n".join(lines)


@dataclass
class Module:
    name: str = ""
    attrs: Tuple[Tuple[str, str], ...] = ()
    computations: Dict[str, Computation] = field(default_factory=dict)
    entry: Optional[str] = None

    def attr(self, key: str) -> Optional[str]:
        for k, v in self.attrs:
            if k == key:
                return v
        return None

    def instructions(self) -> Iterator[Instruction]:
        for comp in self.computations.values():
            yield from comp.instructions.values()

    @property
    def entry_computation(self) -> Optional[Computation]:
        if self.entry is not None:
            return self.computations.get(self.entry)
        return next(iter(self.computations.values()), None)

    def donated_param_count(self) -> int:
        """Number of donated entry parameters, from whichever header form
        this toolchain prints: ``buffer_donor={ (0, {}), ... }`` (the
        pre-optimization print of ``donate_argnums``) or
        ``input_output_alias={ {0}: (0, {}, may-alias), ... }``."""
        n = 0
        for key in ("buffer_donor", "input_output_alias"):
            raw = self.attr(key)
            if raw:
                n = max(n, len(re.findall(r"\(\s*\d+\s*,", raw)))
        return n

    def to_text(self) -> str:
        attrs = "".join(
            f", {k}={v}" if v is not None else f", {k}"
            for k, v in self.attrs)
        out = [f"HloModule {self.name}{attrs}", ""]
        for comp in self.computations.values():
            out.append(comp.to_text())
            out.append("")
        return "\n".join(out)


def _parse_attr_list(s: str) -> Tuple[Tuple[str, str], ...]:
    attrs: List[Tuple[str, str]] = []
    for item in split_top(s):
        item = item.strip()
        if not item:
            continue
        eq = _top_level_eq(item)
        if eq < 0:
            attrs.append((item, None))
        else:
            attrs.append((item[:eq].strip(), item[eq + 1:].strip()))
    return tuple(attrs)


def _top_level_eq(s: str) -> int:
    i = 0
    while i < len(s):
        c = s[i]
        if c == '"':
            i = _scan_string(s, i)
            continue
        if c in _OPEN:
            i = _scan_balanced(s, i)
            continue
        if c == "=":
            return i
        i += 1
    return -1


def _parse_type(rhs: str, i: int) -> Tuple[str, int]:
    """Parse a result type starting at ``rhs[i]``; returns (type, next).
    Types are either a parenthesized tuple or ``dtype[dims]`` with an
    optional layout ``{...}``; returns ("", i) when ``rhs[i]`` does not
    start a type (some prints omit the result type entirely)."""
    start = i
    if i < len(rhs) and rhs[i] == "(":
        j = _scan_balanced(rhs, i)
        return rhs[start:j], j
    m = _OPCODE_RE.match(rhs, i) or _IDENT_RE.match(rhs, i)
    if not m or m.end() >= len(rhs) or rhs[m.end()] != "[":
        return "", i
    j = _scan_balanced(rhs, m.end())
    if j < len(rhs) and rhs[j] == "{":          # layout annotation
        j = _scan_balanced(rhs, j)
    return rhs[start:j], j


def _parse_operand(raw: str) -> Optional[str]:
    """Referenced value name of one operand: the final identifier token
    (the optimized print prefixes the name with its type)."""
    m = _NAME_AT_END_RE.search(raw.strip())
    if not m:
        return None
    return m.group(1).lstrip("%")


def _parse_rhs(rhs: str) -> Tuple[str, str, List[str], List[str],
                                  Tuple[Tuple[str, str], ...]]:
    """``rhs`` of an instruction -> (result_type, opcode, operand names,
    operand raw texts, attrs)."""
    rhs = rhs.strip()
    rtype, i = _parse_type(rhs, 0)
    while i < len(rhs) and rhs[i].isspace():
        i += 1
    m = _OPCODE_RE.match(rhs, i)
    if not m or m.end() >= len(rhs) or rhs[m.end()] != "(":
        raise HloParseError(f"no opcode in instruction RHS: {rhs[:120]!r}")
    opcode = m.group(0)
    j = _scan_balanced(rhs, m.end())
    operand_text = rhs[m.end() + 1:j - 1]
    operands: List[str] = []
    operand_raw: List[str] = []
    for part in split_top(operand_text):
        part = part.strip()
        if not part:
            continue
        operand_raw.append(part)
        name = _parse_operand(part)
        if name is not None:
            operands.append(name)
    rest = rhs[j:].strip()
    if rest.startswith(","):
        rest = rest[1:]
    return rtype, opcode, operands, operand_raw, _parse_attr_list(rest)


def parse(hlo_text: str) -> Module:
    """Parse an HLO module print (either dialect) into a :class:`Module`."""
    mod = Module()
    cur: Optional[Computation] = None
    for line_no, line in enumerate(hlo_text.splitlines(), 1):
        stripped = line.strip()
        if not stripped or stripped.startswith(("//", "#")):
            continue
        if stripped.startswith("HloModule"):
            rest = stripped[len("HloModule"):].strip()
            parts = split_top(rest)
            mod.name = parts[0].strip()
            mod.attrs = _parse_attr_list(",".join(parts[1:]))
            continue
        if stripped == "}":
            cur = None
            continue
        head = _COMP_HEAD_RE.match(line)
        if (not head and stripped.endswith("{") and "=" not in line
                and not stripped.startswith(("while", "if", "for"))):
            # Headers whose param types carry layout annotations nest
            # parens inside the param list and escape the simple regex;
            # any `name (...){` line without `=` is still a header.
            body = stripped[len("ENTRY"):].strip() \
                if stripped.startswith("ENTRY ") else stripped
            first = _IDENT_RE.match(body)
            if first:
                head = _COMP_HEAD_RE.match(
                    ("ENTRY " if stripped.startswith("ENTRY ") else "")
                    + first.group(0) + " {")
        if (head and stripped.endswith("{") and "=" not in line):
            name = head.group("name").lstrip("%")
            cur = Computation(name=name, header=stripped,
                              is_entry=stripped.startswith("ENTRY") or
                              line.lstrip().startswith("ENTRY"))
            if cur.is_entry:
                cur.header = stripped[len("ENTRY"):].strip()
                mod.entry = name
            mod.computations[name] = cur
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        if cur is None:
            # Instructions with no enclosing computation header (snippet
            # inputs, hand-written samples): collect them in an implicit
            # computation.  The `$` keeps the name un-referenceable.
            cur = mod.computations.setdefault(
                "$toplevel", Computation(name="$toplevel",
                                         header="$toplevel {"))
        try:
            rtype, opcode, operands, op_raw, attrs = _parse_rhs(
                m.group("rhs"))
        except HloParseError:
            continue                       # non-instruction noise line
        raw_name = m.group("name")
        ins = Instruction(
            name=raw_name.lstrip("%"), opcode=opcode, result_type=rtype,
            operands=tuple(operands), operand_raw=tuple(op_raw),
            attrs=attrs, is_root=bool(m.group("root")),
            sigil=raw_name.startswith("%"), line_no=line_no)
        cur.instructions[ins.name] = ins
    return mod


# ---------------------------------------------------------------------------
# Concrete byte sizes (structural, tuple-recursive, layout-tolerant)
# ---------------------------------------------------------------------------

_TYPE_COMMENT_RE = re.compile(r"/\*.*?\*/")
_ARRAY_TYPE_RE = re.compile(r"^\s*(\w+)\[([\d,\s]*)\]")


def type_bytes(type_str: Optional[str]) -> int:
    """Concrete byte size of an HLO type string, STRUCTURALLY: a bare
    array shape (layout/tiling braces like ``{1,0:T(8,128)S(1)}``
    tolerated and ignored) or a parenthesized tuple, recursed with the
    same bracket-aware splitter the parser uses — so nested tuples and
    ``/*index=N*/`` element comments (the optimized print) are handled
    by structure, not by regex luck.  ``token[]``/``opaque[]`` and
    dynamic shapes size to 0."""
    s = _TYPE_COMMENT_RE.sub("", type_str or "").strip()
    if not s:
        return 0
    if s.startswith("("):
        inner = s[1:_scan_balanced(s, 0) - 1]
        return sum(type_bytes(part) for part in split_top(inner))
    m = _ARRAY_TYPE_RE.match(s)
    if not m or m.group(1) not in DTYPE_BYTES:
        return 0
    n = DTYPE_BYTES[m.group(1)]
    for d in m.group(2).split(","):
        d = d.strip()
        if d:
            n *= int(d)
    return n


def result_bytes(ins: Instruction) -> int:
    """Bytes of ``ins``'s result buffer(s) — tuple elements summed."""
    return type_bytes(ins.result_type)
