"""Collective-op accounting over the :mod:`analysis.hlo_ir` graph IR.

Same public API and conventions as the historical regex implementation
(``utils/hlo_stats.py``, now a thin adapter over this module — its regex
code survives as ``legacy_*`` oracles for the differential test):

- Byte accounting sums RESULT buffer sizes (tuple elements included): an
  all-gather result is world x the input, which is exactly the gather
  tier's traffic amplification.
- Async pairs are counted once: the ``-start`` op contributes the
  instance count (its result tuple also carries source buffers and would
  overcount bytes), the ``-done`` op contributes the result bytes.
- ``collective_chain_depth`` wants the PRE-OPTIMIZATION print
  (``lowered.compiler_ir(dialect="hlo").as_hlo_text()``), where the
  strategies' ``optimization_barrier`` chains are still data
  dependencies.  Operand chains and called-computation internals COMPOSE
  (sum, not max): a collective chain feeding a collective-bearing while
  body sits at chain + body depth.

Every function accepts either raw HLO text or an already-parsed
:class:`~cs744_ddp_tpu.analysis.hlo_ir.Module`, so audit rules that
share one parse don't re-tokenize per rule.
"""

from __future__ import annotations

import re
from typing import Dict, Union

from . import hlo_ir

# One dtype-width table for the whole analysis stack (hlo_ir owns it).
_DTYPE_BYTES = hlo_ir.DTYPE_BYTES

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")

COLLECTIVE_BASES = ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all")
_COLLECTIVE_OPS = frozenset(
    base + suffix for base in COLLECTIVE_BASES
    for suffix in ("", "-start", "-done"))

ModuleOrText = Union[str, hlo_ir.Module]


def _as_module(hlo: ModuleOrText) -> hlo_ir.Module:
    return hlo if isinstance(hlo, hlo_ir.Module) else hlo_ir.parse(hlo)


def bytes_of_type(type_str: str) -> int:
    """Total bytes of every ``dtype[dims]`` shape in an HLO result type
    (a bare shape or a tuple; layout/tiling annotations are ignored)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(type_str):
        if dtype not in _DTYPE_BYTES:
            continue  # e.g. token[] / opaque[]
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def collective_base(opcode: str) -> Union[str, None]:
    """Base collective name for ``opcode`` (async suffixes stripped), or
    None when the opcode is not a collective."""
    if opcode not in _COLLECTIVE_OPS:
        return None
    return re.sub(r"-(start|done)$", "", opcode)


def collective_weight(opcode: str) -> int:
    """1 for a collective instruction (async start/done pairs counted
    once, on the start), else 0."""
    if opcode.endswith("-done"):
        return 0
    return int(re.sub(r"-start$", "", opcode) in COLLECTIVE_BASES)


def collective_stats(hlo: ModuleOrText) -> Dict:
    """{"ops": {op: {"count", "result_mib"}}, "total_count",
    "total_result_mib"} over every collective instruction in the module."""
    module = _as_module(hlo)
    ops: Dict[str, Dict[str, float]] = {}
    for ins in module.instructions():
        base = collective_base(ins.opcode)
        if base is None:
            continue
        entry = ops.setdefault(base, {"count": 0, "result_mib": 0.0})
        if not ins.opcode.endswith("-done"):
            entry["count"] += 1
        if not ins.opcode.endswith("-start"):
            entry["result_mib"] += bytes_of_type(ins.result_type) / 2**20
    for entry in ops.values():
        entry["result_mib"] = round(entry["result_mib"], 2)
    return {
        "ops": ops,
        "total_count": sum(e["count"] for e in ops.values()),
        "total_result_mib": round(
            sum(e["result_mib"] for e in ops.values()), 2),
    }


def collective_bytes(hlo: ModuleOrText) -> Dict[str, int]:
    """Exact (un-rounded) result bytes per collective base op — what the
    audit's byte contracts compare against parameter sizes; the MiB
    rounding in :func:`collective_stats` zeroes out small test models."""
    module = _as_module(hlo)
    out: Dict[str, int] = {}
    for ins in module.instructions():
        base = collective_base(ins.opcode)
        if base is None or ins.opcode.endswith("-start"):
            continue
        out[base] = out.get(base, 0) + bytes_of_type(ins.result_type)
    return out


def collective_dot_cones(hlo: ModuleOrText) -> Dict:
    """Dots (matmuls/convolutions) in each collective's transitive operand
    cone — the static overlap signature (audit rule for the overlapped
    gradient-sync tier).

    A collective whose cone contains EVERY dot in the program can only
    start after all compute finishes — the single post-backward chain the
    overlap tier exists to break.  A cone missing some dots is a
    collective the latency-hiding scheduler may issue while the remaining
    backward still runs.  Called computations fold in conservatively:
    every dot inside a callee joins the caller instruction's cone.

    Returns {"cones": {"comp/ins": n_dots_in_cone}, "total_dots": N,
    "min_cone": smallest cone (0 when there are no collectives)}.
    """
    module = _as_module(hlo)
    comp_dots: Dict[str, frozenset] = {}

    def all_dots(cname, stack=()) -> frozenset:
        """Every dot id inside computation ``cname``, callees included."""
        if cname in comp_dots:
            return comp_dots[cname]
        if cname in stack or cname not in module.computations:
            return frozenset()
        acc = set()
        for ins in module.computations[cname].instructions.values():
            if ins.opcode in ("dot", "convolution"):
                acc.add(f"{cname}/{ins.name}")
            for c in ins.called:
                acc |= all_dots(c, stack + (cname,))
        comp_dots[cname] = frozenset(acc)
        return comp_dots[cname]

    cones: Dict[str, int] = {}
    total: set = set()
    for cname, comp in module.computations.items():
        local: Dict[str, frozenset] = {}
        for ins in comp.instructions.values():
            cone = set()
            for r in ins.operands:
                cone |= local.get(r, frozenset())
            for c in ins.called:
                cone |= all_dots(c, (cname,))
            if ins.opcode in ("dot", "convolution"):
                cone.add(f"{cname}/{ins.name}")
            local[ins.name] = frozenset(cone)
            if collective_weight(ins.opcode):
                cones[f"{cname}/{ins.name}"] = len(cone)
        total |= all_dots(cname)
    return {
        "cones": cones,
        "total_dots": len(total),
        "min_cone": min(cones.values(), default=0),
    }


def collective_chain_depth(hlo: ModuleOrText) -> int:
    """Longest dependency chain of collectives in the module: the number
    of collectives that must execute SEQUENTIALLY (each consuming a value
    the previous produced), regardless of how many run in total.

    This is the latency SHAPE of a gradient-sync tier, statically: the
    gather tier chains two dependent collectives per parameter leaf
    behind a barrier chain, the per-param all-reduce tier one per leaf,
    the bucketed ddp tier one per bucket.  Computed per computation over
    the SSA def-use graph; operand chains and called-computation
    internals compose by SUM (see module docstring)."""
    module = _as_module(hlo)
    comp_depth: Dict[str, int] = {}

    def depth_of_comp(cname: str, stack=()) -> int:
        if cname in comp_depth:
            return comp_depth[cname]
        if cname in stack:   # recursive reference (shouldn't happen)
            return 0
        comp = module.computations.get(cname)
        d: Dict[str, int] = {}
        best = 0
        if comp is not None:
            for ins in comp.instructions.values():
                operand_chain = 0
                for r in ins.operands:
                    if r in d:
                        operand_chain = max(operand_chain, d[r])
                callee_depth = 0
                for c in ins.called:
                    if c in module.computations and c != cname:
                        callee_depth = max(
                            callee_depth,
                            depth_of_comp(c, stack + (cname,)))
                d[ins.name] = (collective_weight(ins.opcode)
                               + operand_chain + callee_depth)
                best = max(best, d[ins.name])
        comp_depth[cname] = best
        return best

    return max((depth_of_comp(c) for c in module.computations), default=0)
