"""Double-buffered uint8 request staging (the serving ingest path).

Reuses the training pipeline's ``native.StagingArena``: two 64-byte-aligned
host buffers sized to the largest bucket, handed out round-robin with a
per-slot transfer fence, so assembling request batch k+1 overlaps the
device transfer of batch k instead of waiting behind it.  Pad rows are
zeroed at fill time (the engine masks them out by label; zeroing keeps the
staged bytes deterministic so bucketed dispatch is reproducible
byte-for-byte).

The same CPU-client aliasing caveat as training applies: jax's CPU backend
zero-copies suitably aligned committed numpy buffers, and a reused arena
row would then corrupt an in-flight batch.  The arena behavior is probed
EMPIRICALLY once (same design as ``Trainer._probe_put_aliases_host``) and
rows are put as private copies where aliasing is detected — exactly where
no real host->device link exists, so the copy costs nothing that matters.
"""

from __future__ import annotations

import numpy as np

from ..data import native


class StagedIngest:
    """Bounded double-buffered uint8 staging onto the default device."""

    def __init__(self, max_batch: int, nslots: int = 2, device=None):
        self._max_batch = max_batch
        self._arena = native.StagingArena(nslots, 1, max_batch)
        self._put_copies = None   # aliasing probe result, resolved lazily
        self._device = device     # None -> default device (single-engine)

    @property
    def nslots(self) -> int:
        return self._arena.nslots

    def _probe_put_aliases_host(self, buf: np.ndarray) -> bool:
        """Does ``device_put`` of this arena row alias the host memory?
        (See ``native.StagingArena`` docstring; aliasing depends on
        backend + alignment, so it is probed, not assumed.)"""
        import jax
        before = int(buf.flat[0])
        x = jax.device_put(buf, self._device)
        jax.block_until_ready(x)
        buf.flat[0] = np.uint8(before ^ 0xFF)
        aliased = int(np.asarray(jax.device_get(x)).flat[0]) != before
        buf.flat[0] = before
        return aliased

    def stage(self, images: np.ndarray, bucket: int):
        """Fill the next arena slot with ``images`` padded to ``bucket``
        rows (zeros) and start its host->device transfer; returns the
        device array [bucket, 32, 32, 3] uint8."""
        import jax

        n = len(images)
        if not (0 < n <= bucket <= self._max_batch):
            raise ValueError(f"cannot stage {n} images into bucket "
                             f"{bucket} (max {self._max_batch})")
        slot, buf = self._arena.acquire()
        row = buf[0]
        if self._put_copies is None:
            self._put_copies = any(
                self._probe_put_aliases_host(self._arena.buffer(s)[0])
                for s in range(self._arena.nslots))
        row[:n] = images
        if n < bucket:
            row[n:bucket] = 0
        src = row[:bucket]
        handle = jax.device_put(src.copy() if self._put_copies else src,
                                self._device)
        self._arena.retire(slot, handle)
        return handle
