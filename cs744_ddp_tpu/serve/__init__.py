"""Single-chip serving fast path (the ROADMAP north star's other half).

The training side of this repo is evidence-closed; this package is the
first measured serving surface: an AOT-compiled executable ladder over a
fixed set of batch buckets (``engine``), a bounded-queue micro-batcher
that coalesces concurrent requests into the largest ready bucket
(``batcher``), double-buffered uint8 host staging reusing the training
arena (``ingest``), a warm-start executable cache so a restarted server
skips XLA compile (``cache``), and a seeded open-loop demo/measurement
driver (``demo``).

Round 9 grows this into a serving TIER: a continuous-batching SLO
scheduler with priority-tiered admission and deterministic load shedding
(``scheduler``), device-pinned engine replicas with chaos hooks
(``replica``) behind a least-loaded router with death failover
(``router``), and a socket front-end speaking a length-prefixed binary
protocol (``frontend``).

Round 14 makes the dispatch a PIPELINE: the engine splits issue from
completion (``infer_counts_async``/``complete``) and the scheduler keeps
``PIPELINE_SLOTS`` (= 2, the staging arena depth) dispatches in flight
per replica, so batch N+1's host work overlaps batch N's device compute
and the device never idles between buckets.
"""

from .batcher import MicroBatcher, QueueFull, coalesce, plan_batches
from .cache import ExecutableCache, executable_serialization_supported
from .engine import BUCKETS, DispatchHandle, InferenceEngine
from .frontend import FrontendClient, LoopbackClient, ServingFrontend
from .ingest import StagedIngest
from .replica import EngineReplica
from .router import ReplicaRouter
from .scheduler import (PIPELINE_SLOTS, Reply, SchedRequest, ServiceModel,
                        SLOScheduler, admit, cost_model_weights,
                        make_request, plan_continuous, plan_drain,
                        virtual_requests)

__all__ = [
    "BUCKETS", "DispatchHandle", "EngineReplica", "ExecutableCache",
    "FrontendClient", "InferenceEngine", "LoopbackClient", "MicroBatcher",
    "PIPELINE_SLOTS", "QueueFull", "Reply", "ReplicaRouter", "SLOScheduler",
    "SchedRequest", "ServiceModel", "ServingFrontend", "StagedIngest",
    "admit", "coalesce", "cost_model_weights",
    "executable_serialization_supported", "make_request", "plan_batches",
    "plan_continuous", "plan_drain", "virtual_requests",
]
