"""Single-chip serving fast path (the ROADMAP north star's other half).

The training side of this repo is evidence-closed; this package is the
first measured serving surface: an AOT-compiled executable ladder over a
fixed set of batch buckets (``engine``), a bounded-queue micro-batcher
that coalesces concurrent requests into the largest ready bucket
(``batcher``), double-buffered uint8 host staging reusing the training
arena (``ingest``), a warm-start executable cache so a restarted server
skips XLA compile (``cache``), and a seeded open-loop demo/measurement
driver (``demo``).
"""

from .batcher import MicroBatcher, QueueFull, coalesce, plan_batches
from .cache import ExecutableCache, executable_serialization_supported
from .engine import BUCKETS, InferenceEngine
from .ingest import StagedIngest

__all__ = [
    "BUCKETS", "ExecutableCache", "InferenceEngine", "MicroBatcher",
    "QueueFull", "StagedIngest", "coalesce",
    "executable_serialization_supported", "plan_batches",
]
