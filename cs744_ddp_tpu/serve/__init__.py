"""Single-chip serving fast path (the ROADMAP north star's other half).

The training side of this repo is evidence-closed; this package is the
first measured serving surface: an AOT-compiled executable ladder over a
fixed set of batch buckets (``engine``), a bounded-queue micro-batcher
that coalesces concurrent requests into the largest ready bucket
(``batcher``), double-buffered uint8 host staging reusing the training
arena (``ingest``), a warm-start executable cache so a restarted server
skips XLA compile (``cache``), and a seeded open-loop demo/measurement
driver (``demo``).

Round 9 grows this into a serving TIER: a continuous-batching SLO
scheduler with priority-tiered admission and deterministic load shedding
(``scheduler``), device-pinned engine replicas with chaos hooks
(``replica``) behind a least-loaded router with death failover
(``router``), and a socket front-end speaking a length-prefixed binary
protocol (``frontend``).
"""

from .batcher import MicroBatcher, QueueFull, coalesce, plan_batches
from .cache import ExecutableCache, executable_serialization_supported
from .engine import BUCKETS, InferenceEngine
from .frontend import FrontendClient, LoopbackClient, ServingFrontend
from .ingest import StagedIngest
from .replica import EngineReplica
from .router import ReplicaRouter
from .scheduler import (Reply, SchedRequest, ServiceModel, SLOScheduler,
                        admit, cost_model_weights, make_request,
                        plan_continuous, plan_drain, virtual_requests)

__all__ = [
    "BUCKETS", "EngineReplica", "ExecutableCache", "FrontendClient",
    "InferenceEngine", "LoopbackClient", "MicroBatcher", "QueueFull",
    "Reply", "ReplicaRouter", "SLOScheduler", "SchedRequest",
    "ServiceModel", "ServingFrontend", "StagedIngest", "admit", "coalesce",
    "cost_model_weights", "executable_serialization_supported",
    "make_request", "plan_batches", "plan_continuous", "plan_drain",
    "virtual_requests",
]
