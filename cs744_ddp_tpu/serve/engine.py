"""AOT batch-bucketed single-chip inference engine.

Dynamic request sizes meet a compiler that specializes on shapes: compiling
one program per request size would pay XLA compile latency on the serving
path (seconds, vs a sub-millisecond forward).  The standard resolution is a
fixed LADDER of batch buckets (e.g. {1, 8, 32, 128, 256}), every executable
AOT-compiled at startup; a request batch of n images is padded to the
smallest covering bucket and the pad rows are masked out of every reduced
quantity with the SAME label = -1 convention the training eval path uses
(``train/step.py::masked_eval_counts``), so serving and eval accounting
cannot drift apart.  Per-row outputs (logits) are sliced back to n; with
``train=False`` BatchNorm (running stats) every row is computed
independently of its batchmates, so the sliced logits are BITWISE-identical
(f32) to an unpadded direct forward — pinned in tests/test_serve.py.

The forward program mirrors the windowed host path's transfer-compact
design: uint8 in, the normalize fused into the XLA program
(``data/augment.normalize``), optional bf16 compute with f32 logits out.

Warm start: executables are looked up in a ``serve.cache.ExecutableCache``
before compiling (and saved after), on top of the repo-wide persistent XLA
compilation cache — cold vs warm startup seconds are a reported metric
(``bench.py`` serving section), not an anecdote.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from ..models.serving import INGEST_VERSION, make_u8_forward
from ..obs import NULL
from ..utils import compcache
from .cache import ExecutableCache, cache_key
from .ingest import StagedIngest

BUCKETS = (1, 8, 32, 128, 256)

_DTYPES = {"f32": None}  # "bf16" resolved lazily (jnp import)


class DispatchHandle:
    """One in-flight asynchronous dispatch (``infer_counts_async``):
    the device-side result references plus the metadata ``complete``
    needs to fence, slice, and attribute it.  Opaque to callers."""

    __slots__ = ("logits", "loss_sum", "correct", "n", "bucket", "traces",
                 "t_issue")

    def __init__(self, logits, loss_sum, correct, n, bucket, traces,
                 t_issue):
        self.logits = logits
        self.loss_sum = loss_sum
        self.correct = correct
        self.n = n
        self.bucket = bucket
        self.traces = traces
        self.t_issue = t_issue


class InferenceEngine:
    """The executable ladder + padded/masked dispatch for one model.

    ``state`` is a ``TrainState`` (or any object with ``params`` /
    ``bn_state``) — typically restored from a training checkpoint; when
    omitted the model is seed-initialized (the demo/bench mode, where
    latency is the subject and weights are irrelevant).
    """

    def __init__(self, model: str = "vgg11", *,
                 buckets: Sequence[int] = BUCKETS,
                 precisions: Sequence[str] = ("f32",),
                 state=None, seed: int = 0, telemetry=NULL,
                 cache_dir: Optional[str] = None,
                 use_staging: bool = True,
                 enable_compilation_cache: bool = True,
                 device=None):
        import jax
        import jax.numpy as jnp

        from ..models import get_model
        from ..train.step import init_train_state

        if not buckets:
            raise ValueError("need at least one bucket")
        if sorted(set(buckets)) != list(buckets):
            raise ValueError(f"buckets must be strictly increasing, got "
                             f"{tuple(buckets)}")
        for p in precisions:
            if p not in ("f32", "bf16"):
                raise ValueError(f"unknown precision {p!r}")
        if enable_compilation_cache:
            # The repo-wide persistent XLA cache (satellite of the same
            # PR wires it into cli.py startup): dedupes ladder compiles
            # across server restarts even where executable serialization
            # is unsupported.
            compcache.enable_persistent_compilation_cache(compcache.repo_root())
        self.model_name = model
        self.buckets: Tuple[int, ...] = tuple(buckets)
        self.precisions: Tuple[str, ...] = tuple(precisions)
        self.telemetry = telemetry
        init_fn, apply_fn = get_model(model)
        if state is None:
            state = init_train_state(init_fn, jax.random.PRNGKey(seed))
        self.params = state.params
        self.bn_state = state.bn_state
        # Bumped by install_weights() (publish/ hot-swap); tagged into
        # every Reply so the A/B pin is checkable per request.
        self.weights_version = 0
        # Replica pinning: with an explicit device, weights live there and
        # every lowering bakes a SingleDeviceSharding for it, so N replicas
        # occupy N distinct mesh devices instead of piling onto device 0.
        self.device = device
        if device is not None:
            self.params = jax.device_put(self.params, device)
            self.bn_state = jax.device_put(self.bn_state, device)
        self._cache = ExecutableCache(cache_dir)
        self._exec: Dict[Tuple[int, str], Any] = {}
        self._ingest = (StagedIngest(max(self.buckets), device=device)
                        if use_staging else None)
        self._jax = jax

        self._forward = {"f32": make_u8_forward(apply_fn),
                         "bf16": make_u8_forward(apply_fn, jnp.bfloat16)}

        # Everything an executable's identity depends on beyond the bucket
        # and dtype: the abstract model signature (param/bn shapes+dtypes,
        # not values), the fused-ingest scheme, and the toolchain/device
        # identity.
        d0 = device if device is not None else jax.devices()[0]
        leaves, treedef = jax.tree_util.tree_flatten(
            (self.params, self.bn_state))
        self._key_fields = {
            "model": model,
            "ingest": INGEST_VERSION,
            "abstract": (str(treedef),
                         tuple((l.shape, str(l.dtype)) for l in leaves)),
            "jax": jax.__version__,
            "backend": jax.default_backend(),
            "device_kind": getattr(d0, "device_kind", str(d0)),
            "device_id": int(getattr(d0, "id", 0)),
        }

    # -- weight hot-swap ----------------------------------------------------

    def install_weights(self, params, bn_state, version: int, *,
                        assume_staged: bool = False) -> None:
        """Flip the engine's weight references to a new version.

        Weights are runtime ARGUMENTS of the AOT executables (certified
        unbaked by the audit's baked-constants rule), so this is a pure
        reference swap: no executable is touched, nothing recompiles.
        The new tree must match the abstract signature the ladder was
        compiled against — shape/dtype/structure drift would silently
        desync the executables from their arguments, so it is rejected
        here rather than at the next dispatch.

        NOT internally synchronized: the caller must guarantee no
        dispatch is concurrently reading ``self.params`` (the scheduler
        runs installs at its loop boundary via ``request_install``, when
        the worker — the only dispatcher — is provably between batches).

        ``assume_staged=True`` skips the device_put (the watcher stages
        leaves onto this engine's device beforehand, off the serving
        worker's critical path).
        """
        import jax

        leaves, treedef = jax.tree_util.tree_flatten((params, bn_state))
        want_treedef, want_leaves = self._key_fields["abstract"]
        got = (str(treedef), tuple((l.shape, str(l.dtype)) for l in leaves))
        if got != (want_treedef, want_leaves):
            raise ValueError(
                f"install_weights: tree does not match the abstract "
                f"signature the executable ladder was compiled against "
                f"(model {self.model_name!r})")
        if not assume_staged and self.device is not None:
            params = jax.device_put(params, self.device)
            bn_state = jax.device_put(bn_state, self.device)
        self.params = params
        self.bn_state = bn_state
        self.weights_version = int(version)
        if self.telemetry.enabled:
            self.telemetry.counter("weights_installed", version=version)

    # -- ladder -------------------------------------------------------------

    def bucket_for(self, n: int) -> int:
        """Smallest bucket covering ``n`` requests."""
        if n < 1:
            raise ValueError(f"need at least one image, got {n}")
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"request batch {n} exceeds the largest bucket "
                         f"{self.buckets[-1]}; split it upstream "
                         f"(the micro-batcher never builds one this big)")

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    def _abstract_args(self, bucket: int):
        import jax
        import jax.numpy as jnp
        if self.device is not None:
            from jax.sharding import SingleDeviceSharding
            sh = SingleDeviceSharding(self.device)
            to_s = lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype,
                                                  sharding=sh)
            return (jax.tree_util.tree_map(to_s, self.params),
                    jax.tree_util.tree_map(to_s, self.bn_state),
                    jax.ShapeDtypeStruct((bucket, 32, 32, 3), jnp.uint8,
                                         sharding=sh),
                    jax.ShapeDtypeStruct((bucket,), jnp.int32, sharding=sh))
        to_s = lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype)
        return (jax.tree_util.tree_map(to_s, self.params),
                jax.tree_util.tree_map(to_s, self.bn_state),
                jax.ShapeDtypeStruct((bucket, 32, 32, 3), jnp.uint8),
                jax.ShapeDtypeStruct((bucket,), jnp.int32))

    def startup(self) -> dict:
        """Build the whole ladder (cache-load or AOT-compile every
        (bucket, precision) executable); returns the timing report the
        bench's cold/warm startup metric is made of."""
        import jax

        t0 = time.time()
        per: Dict[str, dict] = {}
        for prec in self.precisions:
            for b in self.buckets:
                t1 = time.time()
                key = cache_key(bucket=b, precision=prec,
                                **self._key_fields)
                compiled = self._cache.load(key)
                source = "cache"
                if compiled is None:
                    source = "compile"
                    if self.telemetry.enabled:
                        with self.telemetry.span("serve_compile", bucket=b,
                                                 precision=prec):
                            compiled = self._compile(prec, b)
                    else:
                        compiled = self._compile(prec, b)
                    self._cache.save(key, compiled)
                self._exec[(b, prec)] = compiled
                name = f"{b}/{prec}" if len(self.precisions) > 1 else str(b)
                per[name] = {"seconds": round(time.time() - t1, 4),
                             "source": source}
        report = {
            "startup_s": round(time.time() - t0, 4),
            "per_bucket": per,
            "warm": all(v["source"] == "cache" for v in per.values()),
            "executable_cache": self._cache.stats(),
            "backend": jax.default_backend(),
        }
        if self.telemetry.enabled:
            self.telemetry.gauge("serve_startup_s", report["startup_s"],
                                 warm=report["warm"])
        return report

    def lowered(self, bucket: int, precision: str = "f32"):
        """Pre-compile lowering of one ladder rung — what the program
        auditor (``analysis/audit.audit_serving``) inspects."""
        jit = self._jax.jit(self._forward[precision])
        return jit.lower(*self._abstract_args(bucket))

    def lowered_hlo(self, bucket: int, precision: str = "f32") -> str:
        """Pre-optimization HLO text of one ladder rung."""
        return self.lowered(bucket, precision) \
            .compiler_ir(dialect="hlo").as_hlo_text()

    def _compile(self, precision: str, bucket: int):
        return self.lowered(bucket, precision).compile()

    def _executable(self, bucket: int, precision: str):
        ex = self._exec.get((bucket, precision))
        if ex is None:   # lazy build for direct-use paths without startup()
            key = cache_key(bucket=bucket, precision=precision,
                            **self._key_fields)
            ex = self._cache.load(key)
            if ex is None:
                ex = self._compile(precision, bucket)
                self._cache.save(key, ex)
            self._exec[(bucket, precision)] = ex
        return ex

    # -- dispatch -----------------------------------------------------------

    def _pad_stage(self, images: np.ndarray, bucket: int):
        """Pad the request batch to ``bucket`` rows and move it to device
        (double-buffered arena staging when available; plain padded copy
        otherwise)."""
        if self._ingest is not None:
            return self._ingest.stage(images, bucket)
        padded = np.zeros((bucket, 32, 32, 3), np.uint8)
        padded[:len(images)] = images
        return padded

    def infer_counts(self, images: np.ndarray, labels=None, *,
                     precision: str = "f32",
                     trace_ids: Sequence[int] = ()):
        """Forward a request batch of n <= max_batch images.

        Returns ``(logits[n, 10] f32, loss_sum, correct)``; pad rows carry
        label -1 and contribute NOTHING to loss_sum/correct (the
        ``masked_eval_counts`` convention).  Unlabeled requests (labels
        None) get all -1 labels, so both counts are exactly 0.

        ``trace_ids`` (micro-batcher, telemetry runs) are the riding
        requests' trace ids; the dispatch/fetch spans carry them so every
        device dispatch is attributable to the exact requests it served.
        """
        images = np.ascontiguousarray(images, np.uint8)
        n = images.shape[0]
        bucket = self.bucket_for(n)
        ex = self._executable(bucket, precision)
        padded_labels = np.full((bucket,), -1, np.int32)
        if labels is not None:
            padded_labels[:n] = np.asarray(labels, np.int32)
        tel = self.telemetry
        if tel.enabled:
            tel.counter(f"serve_bucket_{bucket}")
            traces = list(trace_ids)
            with tel.span("serve_stage", bucket=bucket, n=n,
                          traces=traces):
                staged = self._pad_stage(images, bucket)
            with tel.span("serve_dispatch", bucket=bucket, n=n,
                          traces=traces):
                logits, loss_sum, correct = ex(self.params, self.bn_state,
                                               staged, padded_labels)
            with tel.span("serve_fetch", bucket=bucket, traces=traces):
                out = np.asarray(logits)[:n]
                counts = (float(loss_sum), int(correct))
        else:
            staged = self._pad_stage(images, bucket)
            logits, loss_sum, correct = ex(self.params, self.bn_state,
                                           staged, padded_labels)
            out = np.asarray(logits)[:n]
            counts = (float(loss_sum), int(correct))
        return out, counts[0], counts[1]

    # -- pipelined dispatch (issue / complete split) ------------------------

    def infer_counts_async(self, images: np.ndarray, labels=None, *,
                           precision: str = "f32",
                           trace_ids: Sequence[int] = ()) -> DispatchHandle:
        """Issue one padded bucket dispatch WITHOUT fencing it.

        jax dispatch is asynchronous: the executable call returns device
        array futures immediately, so the caller can stage and issue the
        NEXT batch (the second ``StagedIngest`` slot) while this one
        computes.  The two-slot arena bounds the depth: at most
        ``self._ingest.nslots`` dispatches may be in flight before
        ``complete`` retires one (the scheduler enforces exactly 2,
        ``scheduler.PIPELINE_SLOTS``).  Resolve with ``complete(handle)``
        — every issued handle MUST be completed, in issue order, or its
        result (and its arena slot) is leaked.
        """
        images = np.ascontiguousarray(images, np.uint8)
        n = images.shape[0]
        bucket = self.bucket_for(n)
        ex = self._executable(bucket, precision)
        padded_labels = np.full((bucket,), -1, np.int32)
        if labels is not None:
            padded_labels[:n] = np.asarray(labels, np.int32)
        tel = self.telemetry
        traces = tuple(trace_ids)
        if tel.enabled:
            tel.counter(f"serve_bucket_{bucket}")
            with tel.span("serve_stage", bucket=bucket, n=n,
                          traces=list(traces)):
                staged = self._pad_stage(images, bucket)
        else:
            staged = self._pad_stage(images, bucket)
        t_issue = time.time()
        logits, loss_sum, correct = ex(self.params, self.bn_state,
                                       staged, padded_labels)
        return DispatchHandle(logits, loss_sum, correct, n, bucket,
                              traces, t_issue)

    def complete(self, handle: DispatchHandle,
                 prev_done: Optional[float] = None):
        """Fence one in-flight dispatch and fetch its results.

        Returns ``(logits[n, 10] f32, loss_sum, correct, t_ready)`` —
        bitwise-identical rows to the serial ``infer_counts`` path (same
        executable, same staged bytes).  ``prev_done`` (the previous
        completion's ``t_ready``) clips this dispatch's telemetry span to
        the window the device actually worked on it: with two in flight,
        batch N+1's wall interval overlaps batch N's, and the honest
        per-dispatch occupancy is ``t_ready - max(t_issue, prev_done)``
        — what the waterfall's device_compute stage and the scheduler's
        EWMA read.
        """
        self._jax.block_until_ready(handle.logits)
        t_ready = time.time()
        tel = self.telemetry
        if tel.enabled:
            start = handle.t_issue if prev_done is None \
                else max(handle.t_issue, float(prev_done))
            tel.span_event("serve_dispatch", start,
                           max(t_ready - start, 0.0), bucket=handle.bucket,
                           n=handle.n, traces=list(handle.traces))
            with tel.span("serve_fetch", bucket=handle.bucket,
                          traces=list(handle.traces)):
                out = np.asarray(handle.logits)[:handle.n]
                counts = (float(handle.loss_sum), int(handle.correct))
        else:
            out = np.asarray(handle.logits)[:handle.n]
            counts = (float(handle.loss_sum), int(handle.correct))
        return out, counts[0], counts[1], t_ready

    def infer(self, images: np.ndarray, *,
              precision: str = "f32") -> np.ndarray:
        """Logits [n, 10] f32 for n <= max_batch uint8 images."""
        logits, _, _ = self.infer_counts(images, precision=precision)
        return logits
