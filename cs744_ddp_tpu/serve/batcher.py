"""Bounded-queue micro-batcher: coalesce concurrent requests into buckets.

Batching policy (one page, deterministic):

  * requests join a FIFO queue bounded by ``max_queue_images`` — a full
    queue REJECTS (``QueueFull``) instead of buffering unboundedly, the
    standard bounded-staleness choice (Clipper, NSDI'17: reject early so
    tail latency stays bounded);
  * a batch is the longest FIFO prefix whose image total fits the largest
    bucket (requests are atomic — never split across batches);
  * the batch dispatches when the LARGEST bucket is exactly filled, when
    the next queued request cannot fit (the prefix is maximal), or when the
    OLDEST queued request has waited ``max_wait_ms`` — whichever comes
    first.  Latency-throughput tradeoff in one knob: max_wait 0 degenerates
    to per-request dispatch, max_wait inf to full-bucket batching;
  * the dispatched total is padded up to the smallest covering bucket by
    the engine (masked pad rows, ``engine.py``).

The policy lives in two PURE functions — ``coalesce`` (prefix selection)
and ``plan_batches`` (virtual-time replay of a whole arrival trace) — used
by both the threaded runtime and the tests, so batch composition under a
seeded trace is deterministic and CI-pinnable even though thread scheduling
is not.

Telemetry: spans ``serve_enqueue`` -> ``serve_batch`` (assembly) ->
``serve_dispatch`` -> ``serve_fetch`` (the last two in the engine), gauges
``queue_depth`` (images waiting) and ``serve_latency_ms`` per request
(attr ``bucket``), counters ``serve_bucket_<B>`` — all guarded on
``telemetry.enabled`` so the NULL recorder path allocates nothing.

Causality (round 8): every request gets a process-unique ``trace`` id at
submit; the enqueue span carries it, the batch/dispatch/fetch spans carry
the riding batch's full ``traces`` list, and two per-request gauges split
end-to-end latency into ``serve_queue_wait_ms`` (enqueue -> dispatch
start) vs ``serve_service_ms`` (dispatch start -> logits handed back) —
the instrumentation ROADMAP item 1's SLO curves read.
"""

from __future__ import annotations

import itertools
import threading
import time
from concurrent.futures import Future
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..obs import NULL


class QueueFull(RuntimeError):
    """The bounded request queue is at capacity; shed load upstream.

    ``retry_after_ms`` is the backpressure hint: the estimated time for
    the backlog to drain enough to admit the rejected request (queue
    depth x measured service-time EWMA).  The socket front-end forwards
    it verbatim in the wire protocol's overload reply, so clients can
    back off by measurement instead of by guess.
    """

    def __init__(self, msg: str, retry_after_ms: float = 0.0):
        super().__init__(msg)
        self.retry_after_ms = float(retry_after_ms)


def coalesce(sizes: Sequence[int], max_batch: int) -> Tuple[int, int]:
    """Longest FIFO prefix of request ``sizes`` whose total fits
    ``max_batch`` -> (request_count, image_total)."""
    total = 0
    k = 0
    for s in sizes:
        if total + s > max_batch:
            break
        total += s
        k += 1
    return k, total


def smallest_bucket(buckets: Sequence[int], n: int) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"{n} images exceed the largest bucket {buckets[-1]}")


def plan_batches(trace: Sequence[Tuple[float, int]],
                 buckets: Sequence[int],
                 max_wait_s: float) -> List[dict]:
    """Deterministic virtual-time replay of the batching policy over an
    arrival trace ``[(t_arrival, n_images), ...]`` (sorted by time).

    Assumes dispatch itself is instantaneous — this plans batch
    COMPOSITION (which requests ride together, in which bucket, released
    when), the part that must be reproducible under a seeded trace; wall
    clock enters only through the arrival stamps.  Returns
    ``[{"t": dispatch_time, "requests": [trace indices], "images": n,
    "bucket": B}, ...]``.
    """
    max_batch = buckets[-1]
    for t, n in trace:
        if n > max_batch:
            raise ValueError(f"request of {n} images exceeds the largest "
                             f"bucket {max_batch}")
    plan: List[dict] = []
    pending: List[int] = []      # trace indices
    pending_total = 0
    i = 0
    while i < len(trace) or pending:
        if not pending:
            pending = [i]
            pending_total = trace[i][1]
            i += 1
        deadline = trace[pending[0]][0] + max_wait_s
        dispatch_t = None
        while True:
            if pending_total == max_batch:
                dispatch_t = max(trace[pending[-1]][0],
                                 trace[pending[0]][0])
                break
            if i < len(trace) and trace[i][0] <= deadline:
                if pending_total + trace[i][1] > max_batch:
                    # Next request cannot fit: the prefix is maximal.
                    dispatch_t = trace[i][0]
                    break
                pending.append(i)
                pending_total += trace[i][1]
                i += 1
                continue
            dispatch_t = deadline
            break
        plan.append({"t": round(dispatch_t, 9), "requests": pending,
                     "images": pending_total,
                     "bucket": smallest_bucket(buckets, pending_total)})
        pending = []
        pending_total = 0
    return plan


_trace_lock = threading.Lock()
_trace_counter = itertools.count(1)


def next_trace_id() -> int:
    """Process-unique request trace id — the causality key threaded
    through enqueue -> batch -> dispatch -> fetch spans and the
    per-request latency-split gauges."""
    with _trace_lock:
        return next(_trace_counter)


class _Request:
    __slots__ = ("images", "labels", "future", "t_enqueue", "n", "trace",
                 "ctx")

    def __init__(self, images, labels, trace: int, ctx=None):
        self.images = images
        self.labels = labels
        self.n = len(images)
        self.future: Future = Future()
        self.t_enqueue = time.time()
        self.trace = trace
        self.ctx = ctx               # upstream TraceContext, or None


class MicroBatcher:
    """Threaded runtime around the pure policy: one worker drains the
    bounded queue into engine dispatches; ``submit`` returns a Future of
    the request's own logits rows."""

    def __init__(self, engine, *, max_wait_ms: float = 5.0,
                 max_queue_images: int = 1024, telemetry=None,
                 precision: str = "f32"):
        self.engine = engine
        self.max_wait_s = max_wait_ms / 1e3
        self.max_queue_images = max_queue_images
        self.telemetry = telemetry if telemetry is not None \
            else getattr(engine, "telemetry", NULL)
        self.precision = precision
        self._pending: List[_Request] = []
        self._pending_images = 0
        self._cond = threading.Condition()
        self._stop = False
        self._worker: Optional[threading.Thread] = None
        self._svc_ewma_s: Optional[float] = None   # measured dispatch EWMA

    # -- lifecycle ----------------------------------------------------------

    def _assert_owned(self) -> None:
        """Assertion-mode lock-ownership check: every mutation of the
        condition-guarded state (_pending/_pending_images/_stop/_worker)
        must hold ``self._cond``.  ``_enqueue`` reads ``_stop``/``_worker``
        under the lock, so an unlocked writer (the historical
        ``start()``) races; compiled out under ``python -O`` like any
        assert.  The same invariant is enforced statically by the
        ``lock-ownership`` rule in analysis/pylint_rules.py."""
        assert getattr(self._cond, "_is_owned", lambda: True)(), \
            "MicroBatcher shared state mutated without holding self._cond"

    def start(self) -> "MicroBatcher":
        with self._cond:
            if self._worker is not None:
                raise RuntimeError("already started")
            self._assert_owned()
            self._stop = False
            # The worker's first action is to take self._cond, so starting
            # it while we still hold the lock publishes _stop/_worker
            # before it can observe either.
            self._worker = threading.Thread(target=self._run,
                                            name="serve-microbatcher",
                                            daemon=True)
            self._worker.start()
        return self

    def stop(self) -> None:
        """Drain what is queued, then stop the worker."""
        with self._cond:
            self._assert_owned()
            self._stop = True
            worker = self._worker
            self._cond.notify_all()
        if worker is not None:
            worker.join()
            with self._cond:
                self._assert_owned()
                self._worker = None

    def __enter__(self) -> "MicroBatcher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- client side --------------------------------------------------------

    def submit(self, images: np.ndarray, labels=None, *,
               ctx=None) -> Future:
        """Enqueue one request (n <= largest bucket images); the Future
        resolves to this request's logits [n, 10].  Raises ``QueueFull``
        when accepting it would exceed the image bound.  ``ctx``
        (upstream ``TraceContext``) parents this request's queue span
        into the caller's distributed trace."""
        images = np.ascontiguousarray(images, np.uint8)
        n = len(images)
        if n > self.engine.max_batch:
            raise ValueError(f"request of {n} images exceeds the largest "
                             f"bucket {self.engine.max_batch}")
        tel = self.telemetry
        trace = next_trace_id()
        if tel.enabled:
            with tel.span("serve_enqueue", n=n, trace=trace):
                fut = self._enqueue(images, labels, n, trace, ctx)
            with self._cond:
                tel.gauge("queue_depth", self._pending_images)
            return fut
        return self._enqueue(images, labels, n, trace, ctx)

    def _enqueue(self, images, labels, n: int, trace: int,
                 ctx=None) -> Future:
        req = _Request(images, labels, trace, ctx)
        with self._cond:
            if self._worker is None or self._stop:
                raise RuntimeError("micro-batcher is not running")
            if self._pending_images + n > self.max_queue_images:
                raise QueueFull(
                    f"queue holds {self._pending_images} images; adding "
                    f"{n} would exceed the {self.max_queue_images} bound",
                    retry_after_ms=self._retry_after_ms_locked(n))
            self._assert_owned()
            self._pending.append(req)
            self._pending_images += n
            self._cond.notify_all()
        return req.future

    def _retry_after_ms_locked(self, n: int) -> float:
        """Backpressure hint for a rejected request: time for the backlog
        to drain enough to admit ``n`` more images, at one max-bucket
        dispatch per measured service-time EWMA (a conservative 10 ms
        prior before the first dispatch).  Caller holds ``self._cond``."""
        svc = self._svc_ewma_s if self._svc_ewma_s is not None else 0.010
        max_b = self.engine.max_batch
        need = self._pending_images + n - self.max_queue_images
        return round(1e3 * svc * max(1.0, need / float(max_b)), 3)

    # -- worker side --------------------------------------------------------

    def _take_batch(self) -> Optional[List[_Request]]:
        """Block until the policy says dispatch; returns the FIFO prefix
        to dispatch, or None when stopped and drained."""
        max_batch = self.engine.max_batch
        with self._cond:
            while True:
                if self._pending:
                    k, total = coalesce([r.n for r in self._pending],
                                        max_batch)
                    now = time.time()
                    deadline = self._pending[0].t_enqueue + self.max_wait_s
                    if (total == max_batch or k < len(self._pending)
                            or now >= deadline or self._stop):
                        self._assert_owned()
                        batch = self._pending[:k]
                        del self._pending[:k]
                        self._pending_images -= total
                        return batch
                    self._cond.wait(timeout=deadline - now)
                elif self._stop:
                    return None
                else:
                    self._cond.wait()

    def _run(self) -> None:
        tel = self.telemetry
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            try:
                n_images = sum(r.n for r in batch)
                bucket = smallest_bucket(self.engine.buckets, n_images)
                traces = [r.trace for r in batch]
                if tel.enabled:
                    with tel.span("serve_batch", requests=len(batch),
                                  images=n_images, bucket=bucket,
                                  traces=traces):
                        images, labels = self._assemble(batch)
                else:
                    images, labels = self._assemble(batch)
                t_svc0 = time.time()
                if tel.enabled:
                    # trace_ids rides only on the telemetry path: engine
                    # stubs (tests) implement the bare 3-arg signature.
                    logits, _, _ = self.engine.infer_counts(
                        images, labels, precision=self.precision,
                        trace_ids=tuple(traces))
                else:
                    logits, _, _ = self.engine.infer_counts(
                        images, labels, precision=self.precision)
                t_done = time.time()
                with self._cond:
                    prev = self._svc_ewma_s
                    self._svc_ewma_s = (t_done - t_svc0) if prev is None \
                        else 0.7 * prev + 0.3 * (t_done - t_svc0)
                off = 0
                for r in batch:
                    r.future.set_result(logits[off:off + r.n])
                    off += r.n
                    if tel.enabled:
                        tel.gauge("serve_latency_ms",
                                  round((t_done - r.t_enqueue) * 1e3, 3),
                                  bucket=bucket, n=r.n, trace=r.trace)
                        tel.gauge("serve_queue_wait_ms",
                                  round((t_svc0 - r.t_enqueue) * 1e3, 3),
                                  bucket=bucket, n=r.n, trace=r.trace)
                        tel.gauge("serve_service_ms",
                                  round((t_done - t_svc0) * 1e3, 3),
                                  bucket=bucket, n=r.n, trace=r.trace)
                        if r.ctx is not None:
                            tel.span_event(
                                "sched_queue", r.t_enqueue,
                                t_svc0 - r.t_enqueue, trace=r.trace,
                                bucket=bucket,
                                **r.ctx.child("batcher").attrs())
                if tel.enabled:
                    with self._cond:
                        tel.gauge("queue_depth", self._pending_images)
            except BaseException as e:   # noqa: BLE001 - failures go to callers
                for r in batch:
                    if not r.future.done():
                        r.future.set_exception(e)

    @staticmethod
    def _assemble(batch: List[_Request]):
        images = np.concatenate([r.images for r in batch], axis=0)
        labels = np.concatenate([
            np.asarray(r.labels, np.int32) if r.labels is not None
            else np.full((r.n,), -1, np.int32) for r in batch])
        return images, labels
