"""Continuous-batching SLO scheduler over the AOT bucket ladder.

The micro-batcher (``batcher.MicroBatcher``) drains its queue into one
batch per dispatch: requests that arrive while the engine is busy wait for
the NEXT drain, and a 256-image bulk request parks every 1-image request
behind multi-millisecond service no matter how tight their deadlines are.
This module replaces that drain policy with the two serving-systems ideas
this repo's ISSUE cites:

* **Continuous batching** (Orca, Yu et al., OSDI 2022): admission is
  re-decided at every engine-free instant over whatever is queued *now*,
  so new arrivals join the next bucket dispatch instead of waiting for a
  queue drain.  (Orca's per-iteration KV state does not apply here — the
  CNN ladder is stateless — so "iteration-level" degenerates to
  "dispatch-level", which is exactly ``admit()``.)
* **Deadline-aware admission + load shedding** (Clipper, Crankshaw et
  al., NSDI 2017): per-request deadlines and priority tiers; under
  overload the scheduler sheds deterministically — lowest tier first,
  earliest-to-miss first — and every shed request gets an explicit reply.

The policy itself is the pure function ``admit()`` (unit-testable, no
clocks, no locks); ``SLOScheduler`` is the thin threaded shell that runs
it against a real ``InferenceEngine``.  ``plan_continuous`` /
``plan_drain`` replay the same policy (and the old drain policy) in
virtual time over a seeded arrival trace — the deterministic substrate
for the continuous-vs-drain comparison in bench and tests.

Dispatch pipeline (round 14): with ``pipeline=True`` (the default for
engines exposing ``infer_counts_async``/``complete``) the worker keeps up
to ``PIPELINE_SLOTS`` (= 2, the StagedIngest arena depth) dispatches in
flight: batch N+1 is staged into the second arena slot and issued while
batch N computes, and completions resolve strictly in issue order.  The
device never idles between buckets — the host tax (assemble + stage +
issue + fetch) of batch N+1 overlaps batch N's compute.  Honesty
obligations that ride along:

* ``admit(free_at=...)`` deadline-checks a second-slot batch against the
  predicted drain of the work ahead of it, not the admission instant;
* the EWMA observes per-dispatch DEVICE OCCUPANCY
  (``t_ready - max(t_issue, prev_done)``), not the overlapped wall
  interval, so predictions stay additive across slots;
* weight installs (``request_install``) run only when the pipeline is
  fully DRAINED — the engine-free instant between in-flight pairs — so
  the hot-swap A/B pin (no torn weights, per-batch version tag) holds
  under pipelining;
* a fault surfacing at completion of slot N (the ``dispatch_fault``
  chaos site) resolves slot N's requests as explicit errors and slot
  N+1's normally — never a silent drop.
"""

from __future__ import annotations

import itertools
import threading
import time
from concurrent.futures import Future
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ..obs import NULL
from .batcher import QueueFull, next_trace_id, smallest_bucket

_seq_counter = itertools.count(1)

#: Depth of the per-replica dispatch pipeline: one batch computing on
#: device plus one staged-and-issued behind it.  Matches the two-slot
#: ``StagedIngest`` arena (reusing a slot is only safe once the dispatch
#: it fed has been completed); ``analysis/dispatch.py`` certifies the
#: bound statically and tests pin the runtime occupancy against it.
PIPELINE_SLOTS = 2


class SchedRequest:
    """One admitted unit of work: ``n`` images + tier + absolute deadline.

    ``deadline`` is a wall-clock time (``math.inf`` = no SLO); ``seq`` is
    the admission-order tiebreak that makes every policy decision total —
    two requests never compare equal, so ``admit()`` is deterministic.
    """

    __slots__ = ("images", "labels", "n", "tier", "deadline", "t_arrival",
                 "seq", "trace", "future", "ctx", "t_defer")

    def __init__(self, images, labels, n, tier, deadline, t_arrival, seq,
                 trace, future, ctx=None):
        self.images = images
        self.labels = labels
        self.n = n
        self.tier = tier
        self.deadline = deadline
        self.t_arrival = t_arrival
        self.seq = seq
        self.trace = trace
        self.future = future
        self.ctx = ctx            # upstream TraceContext (None = untraced)
        self.t_defer = None       # first admit-deferral wall time


class Reply(NamedTuple):
    """Terminal outcome of one request — every accepted request gets
    exactly one (ok/late/shed/error); the front-end adds "overload" for
    requests rejected at admission."""
    status: str                      # "ok" | "late" | "shed" | "error"
    trace: int = 0
    tier: int = 0
    logits: Optional[np.ndarray] = None
    reason: str = ""
    retry_after_ms: float = 0.0
    queue_wait_ms: float = 0.0
    service_ms: float = 0.0
    latency_ms: float = 0.0
    replica: int = -1
    # Engine weights_version that served this request (publish/ hot-swap
    # A/B pin); -1 for replies that never reached a dispatch (shed/error).
    model_version: int = -1


class Admission(NamedTuple):
    """One ``admit()`` decision: the batch to dispatch now, its bucket,
    the requests shed (with reasons), and the requests DEFERRED back to
    the queue by miss repair (observable for trace attribution — they
    stay pending, so deferral is otherwise invisible queue wait)."""
    batch: Tuple[SchedRequest, ...]
    bucket: Optional[int]
    shed: Tuple[Tuple[SchedRequest, str], ...]
    predicted_done: Optional[float]
    deferred: Tuple[SchedRequest, ...] = ()


def make_request(images, labels=None, *, tier: int = 0,
                 slo_ms: Optional[float] = None, now: Optional[float] = None,
                 seq: Optional[int] = None, trace: Optional[int] = None,
                 max_batch: int = 256, ctx=None) -> SchedRequest:
    """Build a live request (numpy-ified images, fresh Future/trace/seq).
    ``ctx`` is the upstream hop's ``TraceContext`` (or None)."""
    images = np.ascontiguousarray(images, np.uint8)
    n = int(images.shape[0])
    if n < 1:
        raise ValueError("empty request")
    if n > max_batch:
        raise ValueError(f"request of {n} images exceeds the largest "
                         f"bucket {max_batch}; split it client-side")
    if labels is not None:
        labels = np.asarray(labels, np.int32)
        if labels.shape != (n,):
            raise ValueError(f"labels shape {labels.shape} != ({n},)")
    t = time.time() if now is None else float(now)
    deadline = float("inf") if slo_ms is None else t + float(slo_ms) / 1e3
    return SchedRequest(images, labels, n, int(tier), deadline, t,
                        next(_seq_counter) if seq is None else int(seq),
                        next_trace_id() if trace is None else int(trace),
                        Future(), ctx)


def virtual_requests(trace: Sequence[Tuple[float, int, int, float]]
                     ) -> List[SchedRequest]:
    """Futureless requests from a load-trace ``[(t, n, tier, slo_ms), ...]``
    — the input to the virtual-time planners."""
    out = []
    for i, (t, n, tier, slo_ms) in enumerate(trace):
        deadline = float("inf") if slo_ms is None or slo_ms <= 0 \
            else t + slo_ms / 1e3
        out.append(SchedRequest(None, None, int(n), int(tier), deadline,
                                float(t), i, i + 1, None))
    return out


def admit(pending: Sequence[SchedRequest], now: float, *,
          buckets: Sequence[int],
          predict_s: Callable[[int], float],
          shed: bool = True,
          free_at: Optional[float] = None) -> Admission:
    """The continuous-batching admission policy — pure and deterministic.

    Orders the queue by ``(tier, deadline, seq)`` (EDF within tier),
    sheds already-late requests, greedily packs the ladder's largest
    bucket, then repairs predicted misses — re-predicting the (possibly
    smaller) bucket after each removal:

    * first by DEFERRING (back to the queue, not shed) the lowest-
      priority batchmate that is not itself missing — shrinking the
      bucket trades batch throughput for the tight deadline, so a bulk
      background request cannot drag an interactive request past its
      SLO (the Clipper latency/batch-size tradeoff);
    * only when no lower-priority batchmate is left to defer is a miss
      actually shed — always the lowest tier among the missing,
      earliest deadline first.

    Requests that don't fit (or were deferred) stay queued for the next
    admission — that is the "continuous" part.  With ``shed=False``
    nothing is dropped or deferred: late requests are dispatched anyway
    and reported ``late``.

    ``free_at`` (pipelined two-slot admission) is the predicted wall time
    the engine frees a slot for THIS batch: predicted completions are
    measured from ``max(now, free_at)`` instead of ``now``, so a batch
    admitted into the second in-flight slot is deadline-checked against
    when it will actually run, not the admission instant.  ``None`` (the
    serial scheduler, an idle pipeline) keeps the round-13 policy
    bit-for-bit.
    """
    start = now if free_at is None else max(now, float(free_at))
    order = sorted(pending, key=lambda r: (r.tier, r.deadline, r.seq))
    shed_list: List[Tuple[SchedRequest, str]] = []
    live: List[SchedRequest] = []
    if shed:
        for r in order:
            if r.deadline < now:
                shed_list.append((r, "deadline"))
            else:
                live.append(r)
    else:
        live = order
    max_b = buckets[-1]
    batch: List[SchedRequest] = []
    total = 0
    for r in live:
        if total + r.n <= max_b:
            batch.append(r)
            total += r.n
    done = None
    deferred: List[SchedRequest] = []
    while batch:
        done = start + predict_s(smallest_bucket(buckets, total))
        if not shed:
            break
        misses = [r for r in batch if r.deadline < done]
        if not misses:
            break
        urgent = min(r.tier for r in misses)
        defer = [r for r in batch
                 if r.tier > urgent and r.deadline >= done]
        if defer:
            victim = max(defer, key=lambda r: (r.tier, r.deadline, r.seq))
            batch.remove(victim)
            total -= victim.n
            deferred.append(victim)
            done = None
            continue
        worst = max(r.tier for r in misses)
        victim = min((r for r in misses if r.tier == worst),
                     key=lambda r: (r.deadline, r.seq))
        batch.remove(victim)
        total -= victim.n
        shed_list.append((victim, "predicted_miss"))
        done = None
    bucket = smallest_bucket(buckets, total) if batch else None
    return Admission(tuple(batch), bucket, tuple(shed_list), done,
                     tuple(deferred))


# -- virtual-time planners (deterministic replay over a trace) --------------


def _record(r: SchedRequest, status: str, start: float, done: float,
            reason: str = "") -> dict:
    return {"trace": r.trace, "tier": r.tier, "n": r.n, "status": status,
            "reason": reason,
            "queue_wait_ms": round((start - r.t_arrival) * 1e3, 6),
            "t_done": round(done, 9)}


def _summarize_plan(records: List[dict], dispatches: List[dict]) -> dict:
    from ..obs.telemetry import percentile
    waits = sorted(rec["queue_wait_ms"] for rec in records
                   if rec["status"] in ("ok", "late"))
    served = len(waits)
    met = sum(1 for rec in records if rec["status"] == "ok")
    shed = [rec for rec in records if rec["status"] == "shed"]
    return {
        "records": records,
        "dispatches": dispatches,
        "served": served,
        "met": met,
        "shed": [(rec["trace"], rec["tier"], rec["reason"]) for rec in shed],
        "attainment": round(met / len(records), 6) if records else None,
        "p50_wait_ms": round(percentile(waits, 50), 6) if waits else None,
        "p99_wait_ms": round(percentile(waits, 99), 6) if waits else None,
    }


def plan_continuous(requests: Sequence[SchedRequest], *,
                    buckets: Sequence[int],
                    predict_s: Callable[[int], float],
                    shed: bool = True) -> dict:
    """Virtual-time replay of ``admit()`` over an arrival trace: at every
    engine-free instant, re-admit over everything queued.  Deterministic —
    the same trace yields the same dispatches and the same shed set."""
    pend = sorted(requests, key=lambda r: (r.t_arrival, r.seq))
    i, queue = 0, []
    t_free = 0.0
    records: Dict[int, dict] = {}
    dispatches: List[dict] = []
    while i < len(pend) or queue:
        t_now = t_free if queue else max(t_free, pend[i].t_arrival)
        while i < len(pend) and pend[i].t_arrival <= t_now:
            queue.append(pend[i])
            i += 1
        adm = admit(queue, t_now, buckets=buckets, predict_s=predict_s,
                    shed=shed)
        taken = {id(r) for r in adm.batch}
        taken.update(id(r) for r, _ in adm.shed)
        queue = [r for r in queue if id(r) not in taken]
        for r, reason in adm.shed:
            records[r.seq] = _record(r, "shed", t_now, t_now, reason)
        if adm.batch:
            svc = predict_s(adm.bucket)
            done = t_now + svc
            dispatches.append({"t": round(t_now, 9), "bucket": adm.bucket,
                               "traces": tuple(r.trace for r in adm.batch)})
            for r in adm.batch:
                status = "ok" if done <= r.deadline else "late"
                records[r.seq] = _record(r, status, t_now, done)
            t_free = done
        # progress: each iteration dispatches (t_free advances past the
        # next arrival or drains the queue) or sheds >= 1 request.
    ordered = [records[r.seq] for r in pend]
    return _summarize_plan(ordered, dispatches)


def plan_drain(requests: Sequence[SchedRequest], *,
               buckets: Sequence[int],
               predict_s: Callable[[int], float],
               max_wait_s: float = 0.005) -> dict:
    """Virtual-time replay of the micro-batcher's drain policy (FIFO
    prefix-coalesce; dispatch when the prefix is bucket-maximal or the
    oldest request has waited ``max_wait_s``) — the baseline the
    continuous planner is measured against.  No deadlines, no shedding:
    requests that finish past their deadline are simply ``late``."""
    from .batcher import coalesce
    pend = sorted(requests, key=lambda r: (r.t_arrival, r.seq))
    i, queue = 0, []
    t, t_free = 0.0, 0.0
    records: Dict[int, dict] = {}
    dispatches: List[dict] = []
    max_b = buckets[-1]
    while i < len(pend) or queue:
        if not queue:
            t = max(t, pend[i].t_arrival)
            while i < len(pend) and pend[i].t_arrival <= t:
                queue.append(pend[i])
                i += 1
            continue
        k, total = coalesce([r.n for r in queue], max_b)
        expire = queue[0].t_arrival + max_wait_s
        if k < len(queue) or total == max_b:
            start = max(t, t_free)
        elif i < len(pend) and pend[i].t_arrival <= expire:
            t = pend[i].t_arrival
            while i < len(pend) and pend[i].t_arrival <= t:
                queue.append(pend[i])
                i += 1
            continue
        else:
            start = max(expire, t_free, t)
        absorbed = False
        while i < len(pend) and pend[i].t_arrival <= start:
            queue.append(pend[i])
            i += 1
            absorbed = True
        if absorbed:        # engine-busy accumulation: re-coalesce
            t = start
            continue
        batch, queue = queue[:k], queue[k:]
        bucket = smallest_bucket(buckets, total)
        done = start + predict_s(bucket)
        dispatches.append({"t": round(start, 9), "bucket": bucket,
                           "traces": tuple(r.trace for r in batch)})
        for r in batch:
            records[r.seq] = _record(
                r, "ok" if done <= r.deadline else "late", start, done)
        t, t_free = start, done
    ordered = [records[r.seq] for r in pend]
    return _summarize_plan(ordered, dispatches)


# -- service-time model -----------------------------------------------------


class ServiceModel:
    """Per-bucket service-time prior, corrected online by measurement.

    The prior is a *shape*: relative weights per bucket (HLO cost-model
    flops via ``cost_model_weights``, or the bucket sizes themselves)
    anchored at ``anchor_s`` for the smallest bucket.  Every dispatch
    feeds ``observe()``; ``predict()`` prefers the measured EWMA for the
    bucket, then scales from the most-observed measured bucket by the
    weight ratio, then falls back to the anchored prior — so the router's
    outstanding-work estimate starts sane and converges to reality.
    """

    _lock_owned = ("_ewma", "_nobs")

    def __init__(self, buckets: Sequence[int], *,
                 weights: Optional[Dict[int, float]] = None,
                 anchor_s: float = 2e-3, alpha: float = 0.3):
        self.buckets = tuple(int(b) for b in buckets)
        if weights is None:
            weights = {b: float(b) for b in self.buckets}
        missing = [b for b in self.buckets if b not in weights]
        if missing:
            raise ValueError(f"weights missing buckets {missing}")
        self.weights = {b: float(weights[b]) for b in self.buckets}
        self.anchor_s = float(anchor_s)
        self.alpha = float(alpha)
        self._lock = threading.Lock()
        self._ewma: Dict[int, float] = {}
        self._nobs: Dict[int, int] = {}

    def observe(self, bucket: int, service_s: float) -> None:
        b, s = int(bucket), float(service_s)
        with self._lock:
            prev = self._ewma.get(b)
            self._ewma[b] = s if prev is None \
                else (1.0 - self.alpha) * prev + self.alpha * s
            self._nobs[b] = self._nobs.get(b, 0) + 1

    def predict(self, bucket: int) -> float:
        b = int(bucket)
        with self._lock:
            got = self._ewma.get(b)
            if got is not None:
                return got
            if self._nobs:
                ref = max(self._nobs, key=lambda k: (self._nobs[k], k))
                return self._ewma[ref] * self.weights[b] / self.weights[ref]
        return self.anchor_s * self.weights[b] / self.weights[self.buckets[0]]

    def snapshot(self) -> Dict[int, float]:
        """Frozen ``{bucket: predicted_s}`` — a deterministic ``predict_s``
        for the virtual planners."""
        return {b: self.predict(b) for b in self.buckets}


def cost_model_weights(engine, precision: str = "f32") -> Dict[int, float]:
    """Per-bucket HLO-cost-model flops — the static service-time *shape*
    for ``ServiceModel`` (PR 8's analytic cost report, reused as the
    router's prior)."""
    from ..analysis.costmodel import cost_report
    out = {}
    for b in engine.buckets:
        rep = cost_report(engine.lowered_hlo(b, precision), f"serve_b{b}")
        out[int(b)] = max(float(rep.flops), 1.0)
    return out


# -- the threaded scheduler shell ------------------------------------------


class SLOScheduler:
    """Continuous-batching worker over one ``InferenceEngine``.

    One daemon thread re-runs ``admit()`` at every engine-free instant;
    accepted requests resolve their Future with a ``Reply`` exactly once
    (ok / late / shed / error — never silently dropped).  A worker crash
    (including the ``replica_death`` chaos site) hands every unfinished
    request to ``on_death`` — the router's failover hook — or resolves
    them as explicit errors when unattended.

    ``pipeline`` selects the double-buffered worker (module docstring):
    ``None`` auto-enables it when the engine exposes the async dispatch
    API (``infer_counts_async``/``complete``); ``False`` forces the
    serial round-13 worker (the bench A/B baseline and the path engine
    stubs exercise).  ``complete_hook(dispatch_no, bucket)`` runs at each
    dispatch's COMPLETION point; an exception it raises (the
    ``dispatch_fault`` chaos site) is isolated to that one batch —
    explicit error replies, the worker keeps serving — unlike
    ``dispatch_hook`` exceptions, which kill the worker (replica death).
    """

    _lock_owned = ("_pending", "_pending_images", "_inflight", "_stop",
                   "_dead", "_busy_s", "_busy_until", "_worker",
                   "_t0_wall", "_installs")

    def __init__(self, engine, *, svc: Optional[ServiceModel] = None,
                 shed: bool = True, max_queue_images: int = 1024,
                 precision: str = "f32", telemetry=None, replica: int = 0,
                 dispatch_hook=None, complete_hook=None, on_death=None,
                 pipeline: Optional[bool] = None):
        self.engine = engine
        self.buckets = tuple(engine.buckets)
        self.svc = svc if svc is not None else ServiceModel(self.buckets)
        self.shed = bool(shed)
        self.max_queue_images = int(max_queue_images)
        self.precision = precision
        self.telemetry = telemetry if telemetry is not None else NULL
        self.replica = int(replica)
        self.dispatch_hook = dispatch_hook
        self.complete_hook = complete_hook
        self.on_death = on_death
        if pipeline is None:
            pipeline = hasattr(engine, "infer_counts_async")
        elif pipeline and not hasattr(engine, "infer_counts_async"):
            raise ValueError(
                "pipeline=True requires an engine with the async dispatch "
                "API (infer_counts_async/complete)")
        self.pipeline = bool(pipeline)
        self._cond = threading.Condition()
        self._pending: List[SchedRequest] = []
        self._pending_images = 0
        self._inflight: Tuple[SchedRequest, ...] = ()
        self._stop = False
        self._dead = False
        self._busy_s = 0.0
        # Predicted wall time the in-flight pipeline drains (0.0 = idle);
        # feeds admit(free_at=...) and the overload retry hint.
        self._busy_until = 0.0
        self._worker: Optional[threading.Thread] = None
        self._t0_wall: Optional[float] = None
        self._dispatches = 0          # worker-thread-local dispatch index
        # Engine-free-instant work queue (weight installs): closures the
        # worker runs at its next loop boundary, when no dispatch is in
        # flight — the hot-swap's no-torn-reads guarantee.
        self._installs: List[Tuple[Callable[[], object], Future]] = []

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "SLOScheduler":
        with self._cond:
            if self._worker is not None:
                raise RuntimeError("scheduler already started")
            if self._dead:
                raise RuntimeError("scheduler is dead")
            self._stop = False
            self._worker = threading.Thread(
                target=self._run, name=f"slo-sched-{self.replica}",
                daemon=True)
            self._t0_wall = time.time()
            worker = self._worker
        worker.start()
        return self

    def stop(self) -> None:
        """Drain the queue, then stop the worker (idempotent)."""
        with self._cond:
            worker = self._worker
            self._stop = True
            self._cond.notify_all()
        if worker is not None:
            worker.join()
        # Installs queued after the worker's last boundary check would be
        # stranded — run them inline (the worker is gone, so this thread
        # IS the engine-free instant).
        with self._cond:
            leftovers = self._installs
            self._installs = []
        self._run_installs(leftovers)
        t_now = time.time()
        with self._cond:
            self._worker = None
            t0 = self._t0_wall
            busy = self._busy_s
        if t0 is not None and self.telemetry.enabled:
            wall = max(t_now - t0, 1e-9)
            self.telemetry.gauge("replica_busy_s", round(busy, 6),
                                 replica=self.replica)
            self.telemetry.gauge("replica_util",
                                 round(min(busy / wall, 1.0), 6),
                                 replica=self.replica)

    def __enter__(self) -> "SLOScheduler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def alive(self) -> bool:
        return self._worker is not None and not self._dead

    # -- admission ---------------------------------------------------------

    def submit(self, images, labels=None, *, tier: int = 0,
               slo_ms: Optional[float] = None, ctx=None) -> Future:
        """Accept one request; returns a Future resolving to a ``Reply``.
        Raises ``QueueFull`` (with a retry-after hint) when the bounded
        queue cannot take it.  ``ctx`` is the upstream ``TraceContext``
        (the frontend hop's), threaded into dispatch-time spans."""
        req = make_request(images, labels, tier=tier, slo_ms=slo_ms,
                           max_batch=self.engine.max_batch, ctx=ctx)
        return self.enqueue(req)

    def enqueue(self, req: SchedRequest) -> Future:
        """Admit an already-built request (the router's failover path
        re-enqueues the SAME object so trace/deadline/Future survive)."""
        tel = self.telemetry
        hint = None
        with self._cond:
            if self._dead or self._stop:
                raise RuntimeError(
                    f"replica {self.replica} not accepting requests")
            if self._pending_images + req.n > self.max_queue_images:
                hint = self._retry_hint_ms_locked(req.n)
            else:
                self._pending.append(req)
                self._pending_images += req.n
                depth = self._pending_images
                self._cond.notify_all()
        if hint is None and tel.enabled:
            # Queue-depth watermark signal for the alert engine.
            tel.gauge("serve_queue_depth", depth, replica=self.replica)
        if hint is not None:
            if tel.enabled:
                tel.counter("serve_overload", tier=req.tier,
                            replica=self.replica)
            raise QueueFull(
                f"replica {self.replica} queue full "
                f"({self.max_queue_images} images)", retry_after_ms=hint)
        if tel.enabled:
            tel.counter("serve_admitted", tier=req.tier, replica=self.replica)
        return req.future

    def request_install(self, fn: Callable[[], object]) -> Future:
        """Queue ``fn`` (a weight-flip closure from the publish watcher)
        to run at the worker's next engine-free instant — between
        dispatches, so no batch can observe a torn weight tree.  Returns
        a Future resolving to ``fn()``'s result (or its exception).

        With no live worker (not started, stopped, or dead) there is no
        dispatcher to race, so ``fn`` runs inline right here.  Safe to
        call from inside a dispatch hook (the ``swap_mid_batch`` chaos
        probe): the hook runs ON the worker thread, the install is merely
        queued, and it lands after the current dispatch completes — the
        caller must not block on the Future from that context.
        """
        fut: Future = Future()
        inline = False
        with self._cond:
            if self._worker is None or self._dead or self._stop:
                inline = True
            else:
                self._installs.append((fn, fut))
                self._cond.notify_all()
        if inline:
            self._run_installs([(fn, fut)])
        return fut

    @staticmethod
    def _run_installs(installs) -> None:
        for fn, fut in installs:
            if fut.done():
                continue
            try:
                fut.set_result(fn())
            except Exception as exc:   # install failure must not kill serving
                fut.set_exception(exc)

    def _retry_hint_ms_locked(self, n: int) -> float:
        """Time for the backlog to drain enough to admit ``n`` more images
        (queue depth x per-max-bucket service-time estimate, plus the
        predicted drain of any in-flight pipeline slots).  Caller holds
        ``self._cond``."""
        max_b = self.buckets[-1]
        need = self._pending_images + n - self.max_queue_images
        batches = max(1.0, need / float(max_b))
        hint = 1e3 * self.svc.predict(max_b) * batches
        inflight_s = self._busy_until - time.time()
        if inflight_s > 0.0:
            hint += 1e3 * inflight_s
        return round(hint, 3)

    def outstanding_s(self) -> float:
        """Predicted seconds of queued + in-flight work — the router's
        least-loaded signal."""
        with self._cond:
            reqs = list(self._pending) + list(self._inflight)
        pred = self.svc.predict
        return sum(pred(smallest_bucket(self.buckets, r.n)) for r in reqs)

    def queue_depth(self) -> int:
        with self._cond:
            return self._pending_images

    # -- worker ------------------------------------------------------------

    def _run(self) -> None:
        try:
            if self.pipeline:
                self._run_pipelined()
                return
            while True:
                item = self._next_admission()
                if item is None:
                    return
                adm, now = item
                if adm.deferred:
                    self._note_deferred(adm.deferred, now)
                if adm.shed:
                    self._resolve_shed(adm.shed, now)
                if adm.batch:
                    self._dispatch(adm.batch, adm.bucket)
        except Exception as exc:       # replica death: hand off, never drop
            self._die(exc)

    def _next_admission(self):
        while True:
            with self._cond:
                installs = self._installs
                self._installs = []
                if not installs:
                    if self._pending:
                        now = time.time()
                        adm = admit(self._pending, now, buckets=self.buckets,
                                    predict_s=self.svc.predict,
                                    shed=self.shed)
                        taken = {id(r) for r in adm.batch}
                        taken.update(id(r) for r, _ in adm.shed)
                        self._pending = [r for r in self._pending
                                         if id(r) not in taken]
                        self._pending_images = sum(r.n for r in self._pending)
                        self._inflight = adm.batch
                        return adm, now
                    if self._stop:
                        return None
                    self._cond.wait()
                    continue
            # Engine-free instant: no dispatch in flight, lock released
            # (an install may device_put / take its time — admission and
            # enqueue must not stall behind it).
            self._run_installs(installs)

    # -- pipelined worker (two in-flight slots) -----------------------------

    def _run_pipelined(self) -> None:
        """Double-buffered dispatch loop: admit-and-issue into a free slot
        while the oldest dispatch computes; complete strictly in issue
        order.  ``inflight`` (worker-local, oldest first) holds at most
        ``PIPELINE_SLOTS`` issued-but-uncompleted dispatch records."""
        tel = self.telemetry
        inflight: List[dict] = []
        prev_done: Optional[float] = None
        while True:
            op, payload = self._next_pipeline_op(len(inflight))
            if op == "exit":
                return
            if op == "installs":
                # Pipeline fully drained: the engine-free instant between
                # in-flight pairs — the only point a weight flip may land
                # (lock released; an install may device_put at leisure).
                self._run_installs(payload)
                continue
            if op == "complete":
                prev_done = self._complete_oldest(inflight, prev_done)
            else:  # "admit"
                adm, now = payload
                if adm.deferred:
                    self._note_deferred(adm.deferred, now)
                if adm.shed:
                    self._resolve_shed(adm.shed, now)
                if adm.batch:
                    inflight.append(self._issue(adm.batch, adm.bucket))
            if tel.enabled:
                tel.gauge("serve_inflight", len(inflight),
                          replica=self.replica)

    def _next_pipeline_op(self, have: int):
        """Pick the worker's next action under the lock.  Priority: drain
        toward queued installs; admit-and-issue into a free slot; complete
        the oldest in-flight dispatch; exit when stopped and drained."""
        while True:
            with self._cond:
                if self._installs:
                    if have:
                        return "complete", None
                    installs = self._installs
                    self._installs = []
                    return "installs", installs
                if self._pending and have < PIPELINE_SLOTS:
                    now = time.time()
                    adm = admit(self._pending, now, buckets=self.buckets,
                                predict_s=self.svc.predict, shed=self.shed,
                                free_at=self._busy_until if have else None)
                    taken = {id(r) for r in adm.batch}
                    taken.update(id(r) for r, _ in adm.shed)
                    self._pending = [r for r in self._pending
                                     if id(r) not in taken]
                    self._pending_images = sum(r.n for r in self._pending)
                    self._inflight = self._inflight + adm.batch
                    if adm.batch:
                        self._busy_until = max(self._busy_until, now) \
                            + self.svc.predict(adm.bucket)
                    return "admit", (adm, now)
                if have:
                    return "complete", None
                if self._stop:
                    return "exit", None
                self._cond.wait()

    def _issue(self, batch, bucket: int) -> dict:
        """Issue one admitted batch without fencing it: hook, version tag,
        assemble, stage into the next arena slot, async dispatch."""
        t0 = time.time()
        dno = self._dispatches
        hook = self.dispatch_hook
        if hook is not None:
            hook(dno, bucket)
        self._dispatches += 1
        # The version serving THIS batch, read once at issue.  Installs
        # only land when the pipeline is drained, so no install can flip
        # weights between this read and the executable consuming them.
        version = int(getattr(self.engine, "weights_version", -1))
        images, labels = self._assemble(batch)
        traces = tuple(r.trace for r in batch)
        handle = self.engine.infer_counts_async(
            images, labels, precision=self.precision,
            trace_ids=traces if self.telemetry.enabled else ())
        return {"batch": batch, "bucket": bucket, "handle": handle,
                "t0": t0, "version": version, "dispatch": dno,
                "traces": traces}

    def _complete_oldest(self, inflight: List[dict],
                         prev_done: Optional[float]) -> float:
        """Fence, fetch, account, and reply the OLDEST in-flight dispatch.
        A ``complete_hook`` exception (the ``dispatch_fault`` chaos site)
        is isolated to this batch: its requests get explicit error
        replies, the newer in-flight dispatch is untouched, and the old
        weights keep serving.  Returns this completion's ``t_ready`` (the
        next call's ``prev_done``)."""
        rec = inflight.pop(0)
        batch, bucket = rec["batch"], rec["bucket"]
        tel = self.telemetry
        fault = None
        chook = self.complete_hook
        if chook is not None:
            try:
                chook(rec["dispatch"], bucket)
            except Exception as exc:    # isolated: this batch only
                fault = exc
        # Fence and fetch even on a fault: the arena slot and the
        # completion clock must stay consistent (the result is discarded).
        logits, _, _, t_ready = self.engine.complete(
            rec["handle"], prev_done=prev_done)
        t0 = rec["t0"]
        start = t0 if prev_done is None else max(t0, prev_done)
        occ_s = max(t_ready - start, 0.0)   # device occupancy, not wall
        self.svc.observe(bucket, occ_s)
        svc_ms = round((t_ready - t0) * 1e3, 3)
        batch_ids = {id(r) for r in batch}
        with self._cond:
            self._inflight = tuple(r for r in self._inflight
                                   if id(r) not in batch_ids)
            self._busy_s += occ_s
            self._busy_until = t_ready + sum(
                self.svc.predict(r2["bucket"]) for r2 in inflight)
        if tel.enabled:
            tel.gauge("serve_service_ms", round(occ_s * 1e3, 3),
                      bucket=bucket, replica=self.replica,
                      traces=list(rec["traces"]))
            if fault is not None:
                tel.counter("serve_dispatch_fault", bucket=bucket,
                            replica=self.replica,
                            error=type(fault).__name__)
        off = 0
        for r in batch:
            out = logits[off:off + r.n]
            off += r.n
            met = t_ready <= r.deadline
            qw_ms = round((t0 - r.t_arrival) * 1e3, 3)
            lat_ms = round((t_ready - r.t_arrival) * 1e3, 3)
            if tel.enabled:
                tel.gauge("serve_latency_ms", lat_ms, trace=r.trace,
                          tier=r.tier, met=met, replica=self.replica)
                tel.gauge("serve_queue_wait_ms", qw_ms, trace=r.trace,
                          tier=r.tier, replica=self.replica)
                if not met and fault is None:
                    tel.counter("serve_deadline_miss", tier=r.tier,
                                replica=self.replica)
                if r.ctx is not None:
                    tel.span_event("sched_queue", r.t_arrival,
                                   t0 - r.t_arrival, trace=r.trace,
                                   tier=r.tier, replica=self.replica,
                                   bucket=bucket,
                                   **r.ctx.child("sched").attrs())
                    if r.t_defer is not None:
                        tel.span_event("sched_defer", r.t_defer,
                                       t0 - r.t_defer, trace=r.trace,
                                       **r.ctx.child("sched").attrs())
            if r.future is not None and not r.future.done():
                if fault is not None:
                    r.future.set_result(Reply(
                        status="error", trace=r.trace, tier=r.tier,
                        reason=f"{type(fault).__name__}: {fault}",
                        queue_wait_ms=qw_ms, service_ms=svc_ms,
                        latency_ms=lat_ms, replica=self.replica,
                        model_version=rec["version"]))
                else:
                    r.future.set_result(Reply(
                        status="ok" if met else "late", trace=r.trace,
                        tier=r.tier, logits=out, queue_wait_ms=qw_ms,
                        service_ms=svc_ms, latency_ms=lat_ms,
                        replica=self.replica,
                        model_version=rec["version"]))
        return t_ready

    def _note_deferred(self, deferred, now: float) -> None:
        """Stamp first-deferral time on requests miss-repair pushed back
        to the queue — at dispatch the deferral renders as the
        ``sched_defer`` slice of their queue wait."""
        tel = self.telemetry
        for r in deferred:
            if r.t_defer is None:
                r.t_defer = now
            if tel.enabled:
                tel.counter("serve_deferred", tier=r.tier,
                            replica=self.replica)

    def _resolve_shed(self, shed, now: float) -> None:
        tel = self.telemetry
        for req, reason in shed:
            if tel.enabled:
                tel.counter("serve_shed", tier=req.tier, reason=reason,
                            replica=self.replica)
            if req.future is not None and not req.future.done():
                req.future.set_result(Reply(
                    status="shed", trace=req.trace, tier=req.tier,
                    reason=reason, replica=self.replica,
                    queue_wait_ms=round((now - req.t_arrival) * 1e3, 3)))

    @staticmethod
    def _assemble(batch):
        images = np.concatenate([r.images for r in batch], axis=0)
        labels = None
        if any(r.labels is not None for r in batch):
            labels = np.concatenate(
                [r.labels if r.labels is not None
                 else np.full((r.n,), -1, np.int32) for r in batch])
        return images, labels

    def _dispatch(self, batch, bucket: int) -> None:
        tel = self.telemetry
        hook = self.dispatch_hook
        # The service clock starts BEFORE the dispatch hook: a hook stall
        # (``slow_replica`` — a straggling chip) is service time the
        # router's EWMA must learn, not queue wait.
        t0 = time.time()
        dno = self._dispatches
        if hook is not None:
            hook(dno, bucket)
        self._dispatches += 1
        # The version serving THIS batch, read once at dispatch.  Installs
        # only land at loop boundaries (never mid-dispatch), so the value
        # read here is exactly the weights the executable will consume —
        # the per-request A/B pin.  A swap_mid_batch probe fired by the
        # hook above only QUEUES an install; this batch still runs (and is
        # tagged) on the old weights.
        version = int(getattr(self.engine, "weights_version", -1))
        images, labels = self._assemble(batch)
        traces = tuple(r.trace for r in batch)
        if tel.enabled:
            logits, _, _ = self.engine.infer_counts(
                images, labels, precision=self.precision, trace_ids=traces)
        else:
            logits, _, _ = self.engine.infer_counts(
                images, labels, precision=self.precision)
        # Completion point: the serial twin of the pipelined worker's
        # complete-side hook, so the dispatch_fault chaos site fires (and
        # pins bitwise) identically in both modes.  A hook exception is
        # isolated to this batch — explicit error replies, worker lives.
        fault = None
        chook = self.complete_hook
        if chook is not None:
            try:
                chook(dno, bucket)
            except Exception as exc:
                fault = exc
        t_done = time.time()
        svc_s = t_done - t0
        self.svc.observe(bucket, svc_s)
        with self._cond:
            self._inflight = ()
            self._busy_s += svc_s
        if tel.enabled:
            tel.gauge("serve_service_ms", round(svc_s * 1e3, 3),
                      bucket=bucket, replica=self.replica, traces=list(traces))
            if fault is not None:
                tel.counter("serve_dispatch_fault", bucket=bucket,
                            replica=self.replica,
                            error=type(fault).__name__)
        off = 0
        for r in batch:
            out = logits[off:off + r.n]
            off += r.n
            met = t_done <= r.deadline
            qw_ms = round((t0 - r.t_arrival) * 1e3, 3)
            lat_ms = round((t_done - r.t_arrival) * 1e3, 3)
            if tel.enabled:
                tel.gauge("serve_latency_ms", lat_ms, trace=r.trace,
                          tier=r.tier, met=met, replica=self.replica)
                tel.gauge("serve_queue_wait_ms", qw_ms, trace=r.trace,
                          tier=r.tier, replica=self.replica)
                if not met and fault is None:
                    tel.counter("serve_deadline_miss", tier=r.tier,
                                replica=self.replica)
                if r.ctx is not None:
                    # The scheduler hop's spans, parented under the
                    # frontend's context: queue wait (arrival ->
                    # dispatch) and, when miss repair pushed the request
                    # back, the deferred slice of that wait.
                    tel.span_event("sched_queue", r.t_arrival,
                                   t0 - r.t_arrival, trace=r.trace,
                                   tier=r.tier, replica=self.replica,
                                   bucket=bucket,
                                   **r.ctx.child("sched").attrs())
                    if r.t_defer is not None:
                        tel.span_event("sched_defer", r.t_defer,
                                       t0 - r.t_defer, trace=r.trace,
                                       **r.ctx.child("sched").attrs())
            if r.future is not None and not r.future.done():
                if fault is not None:
                    r.future.set_result(Reply(
                        status="error", trace=r.trace, tier=r.tier,
                        reason=f"{type(fault).__name__}: {fault}",
                        queue_wait_ms=qw_ms,
                        service_ms=round(svc_s * 1e3, 3),
                        latency_ms=lat_ms, replica=self.replica,
                        model_version=version))
                else:
                    r.future.set_result(Reply(
                        status="ok" if met else "late", trace=r.trace,
                        tier=r.tier, logits=out, queue_wait_ms=qw_ms,
                        service_ms=round(svc_s * 1e3, 3), latency_ms=lat_ms,
                        replica=self.replica, model_version=version))

    def _die(self, exc: Exception) -> None:
        with self._cond:
            self._dead = True
            self._stop = True
            unfinished = list(self._inflight) + list(self._pending)
            self._inflight = ()
            self._pending = []
            self._pending_images = 0
            installs = self._installs
            self._installs = []
            self._cond.notify_all()
        for _, fut in installs:        # a dead replica installs nothing
            if not fut.done():
                fut.set_exception(RuntimeError(
                    f"replica {self.replica} died before install: {exc}"))
        if self.telemetry.enabled:
            self.telemetry.counter("replica_dead", replica=self.replica,
                                   error=type(exc).__name__)
        cb = self.on_death
        if cb is not None:
            cb(self, unfinished, exc)
            return
        for r in unfinished:
            if r.future is not None and not r.future.done():
                r.future.set_result(Reply(
                    status="error", trace=r.trace, tier=r.tier,
                    reason=f"{type(exc).__name__}: {exc}",
                    replica=self.replica))
