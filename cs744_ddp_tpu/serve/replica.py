"""One serving replica: a device-pinned engine + its SLO scheduler.

Replica scale-out on the mesh: each replica owns an ``InferenceEngine``
compiled FOR one device (``SingleDeviceSharding`` baked into the AOT
lowerings, weights resident on that device) plus a ``ServiceModel`` and
an ``SLOScheduler`` worker thread.  Replicas are independent — no shared
queue, no shared executables — so the router can treat them as
interchangeable chips, and one replica dying (the ``replica_death``
chaos site) takes down exactly its own worker.

Chaos wiring: the scheduler's ``dispatch_hook`` fires this replica's
sites against its OWN dispatch counter — ``slow_replica:STEP:REPLICA``
stalls dispatch STEP by ``slow_stall_s`` (a straggler),
``replica_death:STEP:REPLICA`` raises ``ChaosError`` inside the worker,
exercising the router's failover path (pinned in tests: no accepted
request is silently dropped), and ``swap_mid_batch:STEP:REPLICA``
invokes this replica's weight-watcher probe (``swap_probe``, attached
by ``publish.WeightWatcher``) INSIDE dispatch STEP's hook — a publish
racing a dispatch already being assembled.  The probe only queues the
install, so the racing dispatch is answered bitwise by the OLD weights
and the next by the new — never a mix (pinned in tests/test_publish.py).

``dispatch_fault:STEP:REPLICA`` fires on the scheduler's COMPLETION
hook instead: dispatch STEP's device result is discarded at its fence
point (with the pipelined worker, while dispatch STEP+1 is already in
flight).  The scheduler isolates the fault — STEP's requests get
explicit error replies, STEP+1 resolves normally on the same weights —
pinned bitwise against the serial path in tests/test_ft.py.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

from ..ft.chaos import NULL_CHAOS, ChaosError
from ..obs import NULL
from .engine import BUCKETS, InferenceEngine
from .scheduler import ServiceModel, SLOScheduler, cost_model_weights


class EngineReplica:
    """Engine + scheduler pinned to one mesh device."""

    def __init__(self, index: int, model: str = "vgg11", *,
                 device=None, buckets: Sequence[int] = BUCKETS,
                 precision: str = "f32", seed: int = 0, state=None,
                 telemetry=None, cache_dir: Optional[str] = None,
                 svc: Optional[ServiceModel] = None, cost_prior: bool = False,
                 shed: bool = True, max_queue_images: int = 1024,
                 chaos=NULL_CHAOS, slow_stall_s: float = 0.25,
                 use_staging: bool = True,
                 pipeline: Optional[bool] = None):
        tel = telemetry if telemetry is not None else NULL
        self.index = int(index)
        self.telemetry = tel
        self.chaos = chaos
        self.slow_stall_s = float(slow_stall_s)
        # Non-blocking weight-watcher poll (publish.WeightWatcher attaches
        # it); the swap_mid_batch chaos site calls it inside the dispatch
        # hook to race a publish against a live dispatch.
        self.swap_probe = None
        self.engine = InferenceEngine(
            model, buckets=buckets, precisions=(precision,), state=state,
            seed=seed, telemetry=tel, cache_dir=cache_dir, device=device,
            use_staging=use_staging)
        if svc is None:
            weights = cost_model_weights(self.engine, precision) \
                if cost_prior else None
            svc = ServiceModel(self.engine.buckets, weights=weights)
        self.scheduler = SLOScheduler(
            self.engine, svc=svc, shed=shed,
            max_queue_images=max_queue_images, precision=precision,
            telemetry=tel, replica=self.index,
            dispatch_hook=self._chaos_hook,
            complete_hook=self._complete_chaos_hook,
            pipeline=pipeline)

    def _chaos_hook(self, dispatch_no: int, bucket: int) -> None:
        ch = self.chaos
        if not ch.enabled:
            return
        if dispatch_no in ch.steps("slow_replica") \
                and ch.seed_of("slow_replica", dispatch_no) == self.index \
                and ch.fire("slow_replica", dispatch_no):
            self._note_chaos("slow_replica", dispatch_no)
            time.sleep(self.slow_stall_s)
        if dispatch_no in ch.steps("swap_mid_batch") \
                and ch.seed_of("swap_mid_batch", dispatch_no) == self.index \
                and ch.fire("swap_mid_batch", dispatch_no) \
                and self.swap_probe is not None:
            self._note_chaos("swap_mid_batch", dispatch_no)
            self.swap_probe()
        if dispatch_no in ch.steps("replica_death") \
                and ch.seed_of("replica_death", dispatch_no) == self.index \
                and ch.fire("replica_death", dispatch_no):
            self._note_chaos("replica_death", dispatch_no)
            raise ChaosError(
                f"chaos: replica {self.index} died at dispatch "
                f"{dispatch_no} (bucket {bucket})")

    def _complete_chaos_hook(self, dispatch_no: int, bucket: int) -> None:
        """Completion-side chaos: ``dispatch_fault`` discards dispatch
        ``dispatch_no``'s result at its fence point.  The scheduler
        isolates the raise to that one batch (explicit error replies,
        worker keeps serving) — unlike ``replica_death``, which kills the
        worker from the issue-side hook."""
        ch = self.chaos
        if not ch.enabled:
            return
        if dispatch_no in ch.steps("dispatch_fault") \
                and ch.seed_of("dispatch_fault", dispatch_no) == self.index \
                and ch.fire("dispatch_fault", dispatch_no):
            self._note_chaos("dispatch_fault", dispatch_no)
            raise ChaosError(
                f"chaos: replica {self.index} dispatch {dispatch_no} "
                f"(bucket {bucket}) faulted at completion")

    def _note_chaos(self, site: str, dispatch_no: int) -> None:
        """Chaos firings are themselves telemetry: trace aggregation
        attributes orphaned spans (a death's unfinished requests) and
        straggler stalls to the injection that caused them, instead of
        leaving them indistinguishable from real faults."""
        if self.telemetry.enabled:
            self.telemetry.counter("chaos_fired", site=site,
                                   replica=self.index, dispatch=dispatch_no)

    # -- passthroughs ------------------------------------------------------

    def startup(self) -> dict:
        return self.engine.startup()

    def start(self) -> "EngineReplica":
        self.scheduler.start()
        return self

    def stop(self) -> None:
        self.scheduler.stop()

    def __enter__(self) -> "EngineReplica":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def alive(self) -> bool:
        return self.scheduler.alive

    def outstanding_s(self) -> float:
        return self.scheduler.outstanding_s()

    def enqueue(self, req):
        return self.scheduler.enqueue(req)
