"""Single-source wire-protocol schema table (round 13).

The serving wire protocol is hand-rolled (``serve/frontend.py``: fixed
little-endian struct headers behind a u32 length prefix, variable
payloads counted by a header field, plus the round-12 TLV extension
block from ``obs/tracing.py``).  Drift between an encoder and a decoder
— or between this process and a remote peer built from an older tree —
is the failure mode ROADMAP item 1 (cross-host serving) cannot afford,
and no single test sees it: each side round-trips against itself.

This module is the protocol's ONE declarative description.  Everything
here is a plain literal (no ``struct`` objects, no imports from the
codec modules), so it can be read both at runtime (``verify_runtime()``
cross-checks the live codec constants against the table) and statically
(``analysis/wire_schema.py`` extracts every ``struct`` format and TLV
tag from the codec sources and verifies them against this table without
importing them).  Changing the protocol means changing THIS file plus
the codec — and the conformance checker fails until both agree.

Versioning: the fixed layouts are frozen (old/new peers interop);
anything new rides the TLV extension block under a fresh tag.  Register
the tag here first — tag uniqueness is enforced statically.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Tuple

SCHEMA_VERSION = 1

# -- framing ----------------------------------------------------------------

LENGTH_PREFIX_FMT = "<I"          # u32 frame length, little-endian

# -- fixed-layout frame headers --------------------------------------------


class FrameSchema(NamedTuple):
    """One fixed-layout frame header + its counted variable payload."""

    name: str                     # "request" / "reply"
    fmt: str                      # struct format of the fixed header
    fields: Tuple[str, ...]       # one name per format code, in order
    count_field: str              # header field counting payload items
    item_bytes: int               # bytes per counted payload item
    ext_ok: bool                  # may carry a trailing extension block


REQUEST = FrameSchema(
    name="request",
    fmt="<IBBdH",
    fields=("req_id", "msg", "tier", "slo_ms", "n"),
    count_field="n",
    item_bytes=32 * 32 * 3,       # one u8 HWC CIFAR image
    ext_ok=True,
)

REPLY = FrameSchema(
    name="reply",
    fmt="<IBBQdddiH",
    fields=("req_id", "status", "reason", "trace", "retry_after_ms",
            "queue_wait_ms", "service_ms", "model_version", "n"),
    count_field="n",
    item_bytes=10 * 4,            # one f32[10] logits row
    ext_ok=True,
)

FRAMES = (REQUEST, REPLY)

MSG_INFER = 1

STATUS_CODES = {"ok": 0, "late": 1, "shed": 2, "overload": 3, "error": 4}
REASON_CODES = {"": 0, "deadline": 1, "predicted_miss": 2, "queue_full": 3,
                "internal": 4}

# -- TLV extension block ----------------------------------------------------

EXT_MAGIC = 0xE1
EXT_VERSION = 1
EXT_HEADER_FMT = "<BB"            # magic u8 | version u8
TLV_HEADER_FMT = "<BH"            # tag u8 | len u16


class TLVSchema(NamedTuple):
    """One registered extension field."""

    tag: int
    name: str
    fmt: str                      # struct format of the fixed prefix
    trailing: str                 # "" or a description of trailing bytes


EXT_FIELDS = (
    TLVSchema(tag=1, name="trace", fmt="<QQQ",
              trailing="origin utf-8 (<= 255 B)"),
    TLVSchema(tag=2, name="server_times", fmt="<dd", trailing=""),
)

# Every struct format a codec module is ALLOWED to own, by constant name.
# The static checker resolves each ``struct.Struct("...")`` assignment in
# the codec sources against this registry; an unregistered format (or a
# registered name bound to a different format) is a conformance failure.
REGISTERED_FORMATS: Dict[str, str] = {
    "_LEN": LENGTH_PREFIX_FMT,
    "_REQ": REQUEST.fmt,
    "_REP": REPLY.fmt,
    "_EXT_HEAD": EXT_HEADER_FMT,
    "_TLV_HEAD": TLV_HEADER_FMT,
    "_TRACE_IDS": EXT_FIELDS[0].fmt,
    "_TIMES": EXT_FIELDS[1].fmt,
}

# Registered TAG_* constants, by name (uniqueness enforced statically).
REGISTERED_TAGS: Dict[str, int] = {
    "TAG_TRACE": EXT_FIELDS[0].tag,
    "TAG_SERVER_TIMES": EXT_FIELDS[1].tag,
}


def schema_summary() -> dict:
    """JSON-ready schema description (BASELINE.md / --verify-static)."""
    return {
        "schema_version": SCHEMA_VERSION,
        "length_prefix": LENGTH_PREFIX_FMT,
        "frames": [{"name": f.name, "fmt": f.fmt, "fields": list(f.fields),
                    "count_field": f.count_field,
                    "item_bytes": f.item_bytes} for f in FRAMES],
        "ext": {"magic": EXT_MAGIC, "version": EXT_VERSION,
                "fields": [{"tag": t.tag, "name": t.name, "fmt": t.fmt,
                            "trailing": t.trailing} for t in EXT_FIELDS]},
        "status_codes": dict(STATUS_CODES),
        "reason_codes": dict(REASON_CODES),
    }


def verify_runtime() -> List[str]:
    """Cross-check the LIVE codec constants against this table; returns
    mismatch descriptions ([] = clean).  The runtime complement of the
    static extraction in ``analysis/wire_schema.py`` — together they pin
    source, bytecode, and table to one protocol."""
    from ..obs import tracing
    from . import frontend

    problems: List[str] = []

    def chk(what: str, got, want) -> None:
        if got != want:
            problems.append(f"{what}: runtime {got!r} != schema {want!r}")

    chk("request fmt", frontend._REQ.format, REQUEST.fmt)
    chk("reply fmt", frontend._REP.format, REPLY.fmt)
    chk("length prefix", frontend._LEN.format, LENGTH_PREFIX_FMT)
    chk("image bytes", frontend.IMAGE_BYTES, REQUEST.item_bytes)
    chk("MSG_INFER", frontend.MSG_INFER, MSG_INFER)
    chk("status codes", frontend.STATUS_CODES, STATUS_CODES)
    chk("reason codes", frontend.REASON_CODES, REASON_CODES)
    chk("ext magic", tracing.EXT_MAGIC, EXT_MAGIC)
    chk("ext version", tracing.EXT_VERSION, EXT_VERSION)
    chk("ext header fmt", tracing._EXT_HEAD.format, EXT_HEADER_FMT)
    chk("tlv header fmt", tracing._TLV_HEAD.format, TLV_HEADER_FMT)
    chk("TAG_TRACE", tracing.TAG_TRACE, REGISTERED_TAGS["TAG_TRACE"])
    chk("TAG_SERVER_TIMES", tracing.TAG_SERVER_TIMES,
        REGISTERED_TAGS["TAG_SERVER_TIMES"])
    chk("trace payload fmt", tracing._TRACE_IDS.format, EXT_FIELDS[0].fmt)
    chk("times payload fmt", tracing._TIMES.format, EXT_FIELDS[1].fmt)
    return problems
