"""Seeded synthetic request traces + the open-loop serving demo driver.

The serving numbers (bench.py ``serving`` section, ``cli.py
--serve-demo``) come from replaying a DETERMINISTIC trace: Poisson
arrivals at a configured offered load, request sizes drawn from a fixed
mixture skewed toward small requests (the shape batched serving exists
for), images sampled from the synthetic CIFAR stand-in.  Open loop:
requests are submitted at their scheduled arrival times regardless of
completion (offered load is the independent variable; queueing shows up
in latency, not in a throttled arrival rate).  The driver records
client-side latency (submit -> result) plus its own scheduling lag so a
saturated single-core host cannot silently masquerade as a fast server.

``python -m cs744_ddp_tpu.serve.demo --startup-probe ...`` prints one
JSON line with the engine startup report — bench.py runs it twice in
fresh subprocesses (same cache dirs) to measure COLD vs WARM startup
honestly, outside any in-process jit cache.
"""

from __future__ import annotations

import json
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..data import cifar10
from ..obs import Telemetry
from ..obs.telemetry import percentile
from .batcher import MicroBatcher, QueueFull
from .engine import BUCKETS, InferenceEngine

# Request-size mixture: mostly singletons and small groups, occasional
# bulk requests — uniform over this tuple (seeded), mean ~8 images.
SIZE_CHOICES = (1, 1, 1, 2, 4, 8, 16, 32)


def request_pool(n_images: int = 2048, seed: int = 123) -> cifar10.Split:
    """A small labeled image pool requests sample from (synthetic split —
    generation is deterministic in ``seed``)."""
    return cifar10._synthetic_split(n_images, seed=seed)


def synthetic_trace(n_requests: int, *, offered_rps: float, seed: int,
                    size_choices: Sequence[int] = SIZE_CHOICES
                    ) -> List[Tuple[float, int]]:
    """Seeded open-loop arrival trace: ``[(t_arrival_s, n_images), ...]``
    with Exp(1/offered_rps) inter-arrivals, t starting at 0."""
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / offered_rps, size=n_requests)
    gaps[0] = 0.0
    times = np.cumsum(gaps)
    sizes = rng.choice(np.asarray(size_choices, np.int64), size=n_requests)
    return [(float(t), int(s)) for t, s in zip(times, sizes)]


def run_demo(engine: InferenceEngine, *, n_requests: int = 200,
             offered_rps: float = 20.0, seed: int = 0,
             max_wait_ms: float = 5.0, max_queue_images: int = 1024,
             pool: Optional[cifar10.Split] = None,
             precision: str = "f32") -> dict:
    """Replay one seeded open-loop trace through the micro-batcher;
    returns the latency/throughput stats sheet."""
    pool = pool if pool is not None else request_pool()
    sizes = tuple(s for s in SIZE_CHOICES if s <= engine.max_batch)
    trace = synthetic_trace(n_requests, offered_rps=offered_rps, seed=seed,
                            size_choices=sizes)
    rng = np.random.default_rng(seed + 1)
    requests = []
    for _, size in trace:
        idx = rng.integers(0, len(pool.images), size=size)
        requests.append((pool.images[idx], pool.labels[idx]))

    results: List[Optional[float]] = [None] * len(trace)
    rejected = 0
    driver_lag_max = 0.0

    def make_cb(i: int, t_submit: float):
        def cb(fut):
            if fut.exception() is None:
                results[i] = time.time() - t_submit
        return cb

    with MicroBatcher(engine, max_wait_ms=max_wait_ms,
                      max_queue_images=max_queue_images,
                      precision=precision) as mb:
        t0 = time.time()
        for i, ((t_arr, _size), (imgs, labs)) in enumerate(
                zip(trace, requests)):
            delay = t0 + t_arr - time.time()
            if delay > 0:
                time.sleep(delay)
            else:
                driver_lag_max = max(driver_lag_max, -delay)
            try:
                fut = mb.submit(imgs, labs)
            except QueueFull:
                rejected += 1
                continue
            fut.add_done_callback(make_cb(i, time.time()))
        # stop() drains the queue before returning.
    t_end = time.time()

    lat_ms = [r * 1e3 for r in results if r is not None]
    total_images = sum(s for _, s in trace)
    done_images = sum(s for (_, s), r in zip(trace, results)
                      if r is not None)
    out = {
        "n_requests": n_requests,
        "offered_rps": offered_rps,
        "seed": seed,
        "max_wait_ms": max_wait_ms,
        "completed": len(lat_ms),
        "rejected": rejected,
        "total_images": total_images,
        "achieved_rps": round(len(lat_ms) / (t_end - t0), 2),
        "images_per_sec": round(done_images / (t_end - t0), 2),
        "driver_lag_ms_max": round(driver_lag_max * 1e3, 3),
    }
    if lat_ms:
        out["latency_ms"] = {
            "p50": round(percentile(lat_ms, 50), 3),
            "p95": round(percentile(lat_ms, 95), 3),
            "p99": round(percentile(lat_ms, 99), 3),
            "mean": round(sum(lat_ms) / len(lat_ms), 3),
            "max": round(max(lat_ms), 3),
        }
    tel = engine.telemetry
    if tel.enabled:
        totals = getattr(tel, "counter_totals", lambda: {})()
        out["bucket_counts"] = {
            k.replace("serve_bucket_", ""): int(v)
            for k, v in sorted(totals.items())
            if k.startswith("serve_bucket_")}
    return out


def parse_buckets(spec: str) -> Tuple[int, ...]:
    return tuple(sorted({int(b) for b in spec.split(",") if b.strip()}))


def startup_probe(model: str, *, buckets=BUCKETS, precisions=("f32",),
                  cache_dir: Optional[str] = None, seed: int = 0,
                  telemetry=None) -> dict:
    """Build the ladder once and report the startup timing sheet."""
    engine = InferenceEngine(model, buckets=buckets, precisions=precisions,
                             cache_dir=cache_dir, seed=seed,
                             telemetry=telemetry or Telemetry())
    return engine.startup()


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser("serve.demo")
    p.add_argument("--startup-probe", action="store_true",
                   help="build the executable ladder, print the startup "
                        "timing report as one JSON line, exit (bench.py "
                        "runs this twice in fresh subprocesses for the "
                        "cold/warm startup metric)")
    p.add_argument("--model", default="vgg11")
    p.add_argument("--buckets", default=",".join(map(str, BUCKETS)))
    p.add_argument("--precisions", default="f32",
                   help="comma list from {f32, bf16}")
    p.add_argument("--cache-dir", default=None,
                   help="executable-cache directory (warm start)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--requests", type=int, default=200)
    p.add_argument("--load", type=float, default=20.0,
                   help="offered load, requests/sec (open loop)")
    p.add_argument("--max-wait-ms", type=float, default=5.0)
    args = p.parse_args(argv)

    buckets = parse_buckets(args.buckets)
    precisions = tuple(args.precisions.split(","))
    tel = Telemetry()
    engine = InferenceEngine(args.model, buckets=buckets,
                             precisions=precisions,
                             cache_dir=args.cache_dir, seed=args.seed,
                             telemetry=tel)
    report = engine.startup()
    if args.startup_probe:
        print(json.dumps(report))
        return 0
    stats = run_demo(engine, n_requests=args.requests,
                     offered_rps=args.load, seed=args.seed,
                     max_wait_ms=args.max_wait_ms)
    print(json.dumps({"startup": report, "demo": stats}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
