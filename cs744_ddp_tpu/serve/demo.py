"""Seeded synthetic request traces + the open-loop serving demo driver.

The serving numbers (bench.py ``serving`` section, ``cli.py
--serve-demo``) come from replaying a DETERMINISTIC trace: Poisson
arrivals at a configured offered load, request sizes drawn from a fixed
mixture skewed toward small requests (the shape batched serving exists
for), images sampled from the synthetic CIFAR stand-in.  Open loop:
requests are submitted at their scheduled arrival times regardless of
completion (offered load is the independent variable; queueing shows up
in latency, not in a throttled arrival rate).  The driver records
client-side latency (submit -> result) plus its own scheduling lag so a
saturated single-core host cannot silently masquerade as a fast server.

``python -m cs744_ddp_tpu.serve.demo --startup-probe ...`` prints one
JSON line with the engine startup report — bench.py runs it twice in
fresh subprocesses (same cache dirs) to measure COLD vs WARM startup
honestly, outside any in-process jit cache.
"""

from __future__ import annotations

import json
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..data import cifar10
from ..obs import Telemetry
from ..obs.telemetry import percentile
from .batcher import MicroBatcher, QueueFull
from .engine import BUCKETS, InferenceEngine

# Request-size mixture: mostly singletons and small groups, occasional
# bulk requests — uniform over this tuple (seeded), mean ~8 images.
SIZE_CHOICES = (1, 1, 1, 2, 4, 8, 16, 32)


def request_pool(n_images: int = 2048, seed: int = 123) -> cifar10.Split:
    """A small labeled image pool requests sample from (synthetic split —
    generation is deterministic in ``seed``)."""
    return cifar10._synthetic_split(n_images, seed=seed)


def synthetic_trace(n_requests: int, *, offered_rps: float, seed: int,
                    size_choices: Sequence[int] = SIZE_CHOICES
                    ) -> List[Tuple[float, int]]:
    """Seeded open-loop arrival trace: ``[(t_arrival_s, n_images), ...]``
    with Exp(1/offered_rps) inter-arrivals, t starting at 0."""
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / offered_rps, size=n_requests)
    gaps[0] = 0.0
    times = np.cumsum(gaps)
    sizes = rng.choice(np.asarray(size_choices, np.int64), size=n_requests)
    return [(float(t), int(s)) for t, s in zip(times, sizes)]


# Priority tiers for the serving-load traces: (tier, weight, slo_ms).
# Tier 0 is interactive (tight SLO, small share), tier 2 is background
# bulk (loose SLO) — the mix Clipper-style shedding is judged against.
DEFAULT_TIERS = ((0, 2, 75.0), (1, 5, 200.0), (2, 3, 600.0))


def synthetic_load_trace(n_requests: int, *, offered_rps: float, seed: int,
                         size_choices: Sequence[int] = SIZE_CHOICES,
                         tiers=DEFAULT_TIERS
                         ) -> List[Tuple[float, int, int, float]]:
    """Seeded tiered open-loop trace ``[(t_s, n_images, tier, slo_ms),...]``
    — ``synthetic_trace`` arrivals with priority tiers drawn from the
    weighted ``tiers`` mixture.  Deterministic in (seed, offered_rps)."""
    base = synthetic_trace(n_requests, offered_rps=offered_rps, seed=seed,
                           size_choices=size_choices)
    rng = np.random.default_rng(seed + 17)
    weights = np.asarray([w for _, w, _ in tiers], np.float64)
    picks = rng.choice(len(tiers), size=n_requests, p=weights / weights.sum())
    return [(t, n, int(tiers[k][0]), float(tiers[k][2]))
            for (t, n), k in zip(base, picks)]


def replay_load(client, trace, *, pool: Optional[cifar10.Split] = None,
                seed: int = 0, drain_timeout_s: float = 120.0) -> dict:
    """Open-loop replay of a tiered load trace against a serving client
    (``LoopbackClient`` or ``FrontendClient`` — anything whose
    ``submit(images, tier=, slo_ms=)`` returns a Future of a reply dict).

    Every submitted request is awaited to a terminal reply — the
    accounting fields (``replies`` == ``n_requests``, ``unresolved`` == 0,
    unique trace ids) are the no-silent-drop CI pin.  Goodput counts only
    requests served WITHIN their SLO (status ``ok``)."""
    pool = pool if pool is not None else request_pool()
    rng = np.random.default_rng(seed + 1)
    batches = [pool.images[rng.integers(0, len(pool.images), size=n)]
               for (_t, n, _tier, _slo) in trace]
    entries = []
    driver_lag_max = 0.0
    t0 = time.time()
    for (t_arr, n, tier, slo_ms), imgs in zip(trace, batches):
        delay = t0 + t_arr - time.time()
        if delay > 0:
            time.sleep(delay)
        else:
            driver_lag_max = max(driver_lag_max, -delay)
        fut = client.submit(imgs, tier=tier, slo_ms=slo_ms)
        entries.append((tier, n, fut))
    hard_deadline = time.time() + drain_timeout_s
    replies = []
    unresolved = 0
    for tier, n, fut in entries:
        try:
            rep = fut.result(timeout=max(0.1, hard_deadline - time.time()))
        except Exception:
            rep, unresolved = None, unresolved + 1
        replies.append((tier, n, rep))
    t_end = time.time()

    tiers_seen = sorted({tier for tier, _n, _r in replies})
    by_tier = {}
    for t in tiers_seen:
        mine = [(n, r) for tier, n, r in replies if tier == t]
        counts = {"offered": len(mine)}
        for status in ("ok", "late", "shed", "overload", "error"):
            counts[status] = sum(1 for _n, r in mine
                                 if r is not None and r["status"] == status)
        counts["attainment"] = round(counts["ok"] / counts["offered"], 4)
        by_tier[t] = counts
    ok = [(tier, n, r) for tier, n, r in replies
          if r is not None and r["status"] == "ok"]
    waits = sorted(r["queue_wait_ms"] for _t, _n, r in ok)
    traces = [r["trace"] for _t, _n, r in replies
              if r is not None and r.get("trace")]
    span = trace[-1][0] if trace else 0.0
    wall = max(t_end - t0, 1e-9)
    out = {
        "n_requests": len(trace),
        "offered_rps": round(len(trace) / max(span, 1e-9), 2),
        "wall_s": round(wall, 3),
        "goodput_rps": round(len(ok) / wall, 2),
        "goodput_ips": round(sum(n for _t, n, _r in ok) / wall, 2),
        "attainment": round(len(ok) / len(trace), 4) if trace else None,
        "by_tier": by_tier,
        "shed": sum(c["shed"] for c in by_tier.values()),
        "overload": sum(c["overload"] for c in by_tier.values()),
        "driver_lag_ms_max": round(driver_lag_max * 1e3, 3),
        # No-silent-drop accounting: one terminal reply per submit, and
        # the served/shed replies carry process-unique trace ids.
        "replies": len(replies) - unresolved,
        "unresolved": unresolved,
        "unique_traces": len(set(traces)),
        "traced": len(traces),
    }
    if waits:
        out["queue_wait_ms"] = {"p50": round(percentile(waits, 50), 3),
                                "p99": round(percentile(waits, 99), 3)}
    return out


def run_demo(engine: InferenceEngine, *, n_requests: int = 200,
             offered_rps: float = 20.0, seed: int = 0,
             max_wait_ms: float = 5.0, max_queue_images: int = 1024,
             pool: Optional[cifar10.Split] = None,
             precision: str = "f32") -> dict:
    """Replay one seeded open-loop trace through the micro-batcher;
    returns the latency/throughput stats sheet."""
    pool = pool if pool is not None else request_pool()
    sizes = tuple(s for s in SIZE_CHOICES if s <= engine.max_batch)
    trace = synthetic_trace(n_requests, offered_rps=offered_rps, seed=seed,
                            size_choices=sizes)
    rng = np.random.default_rng(seed + 1)
    requests = []
    for _, size in trace:
        idx = rng.integers(0, len(pool.images), size=size)
        requests.append((pool.images[idx], pool.labels[idx]))

    results: List[Optional[float]] = [None] * len(trace)
    rejected = 0
    driver_lag_max = 0.0

    def make_cb(i: int, t_submit: float):
        def cb(fut):
            if fut.exception() is None:
                results[i] = time.time() - t_submit
        return cb

    with MicroBatcher(engine, max_wait_ms=max_wait_ms,
                      max_queue_images=max_queue_images,
                      precision=precision) as mb:
        t0 = time.time()
        for i, ((t_arr, _size), (imgs, labs)) in enumerate(
                zip(trace, requests)):
            delay = t0 + t_arr - time.time()
            if delay > 0:
                time.sleep(delay)
            else:
                driver_lag_max = max(driver_lag_max, -delay)
            try:
                fut = mb.submit(imgs, labs)
            except QueueFull:
                rejected += 1
                continue
            fut.add_done_callback(make_cb(i, time.time()))
        # stop() drains the queue before returning.
    t_end = time.time()

    lat_ms = [r * 1e3 for r in results if r is not None]
    total_images = sum(s for _, s in trace)
    done_images = sum(s for (_, s), r in zip(trace, results)
                      if r is not None)
    out = {
        "n_requests": n_requests,
        "offered_rps": offered_rps,
        "seed": seed,
        "max_wait_ms": max_wait_ms,
        "completed": len(lat_ms),
        "rejected": rejected,
        "total_images": total_images,
        "achieved_rps": round(len(lat_ms) / (t_end - t0), 2),
        "images_per_sec": round(done_images / (t_end - t0), 2),
        "driver_lag_ms_max": round(driver_lag_max * 1e3, 3),
    }
    if lat_ms:
        out["latency_ms"] = {
            "p50": round(percentile(lat_ms, 50), 3),
            "p95": round(percentile(lat_ms, 95), 3),
            "p99": round(percentile(lat_ms, 99), 3),
            "mean": round(sum(lat_ms) / len(lat_ms), 3),
            "max": round(max(lat_ms), 3),
        }
    tel = engine.telemetry
    if tel.enabled:
        totals = getattr(tel, "counter_totals", lambda: {})()
        out["bucket_counts"] = {
            k.replace("serve_bucket_", ""): int(v)
            for k, v in sorted(totals.items())
            if k.startswith("serve_bucket_")}
    return out


def parse_buckets(spec: str) -> Tuple[int, ...]:
    return tuple(sorted({int(b) for b in spec.split(",") if b.strip()}))


def startup_probe(model: str, *, buckets=BUCKETS, precisions=("f32",),
                  cache_dir: Optional[str] = None, seed: int = 0,
                  telemetry=None) -> dict:
    """Build the ladder once and report the startup timing sheet."""
    engine = InferenceEngine(model, buckets=buckets, precisions=precisions,
                             cache_dir=cache_dir, seed=seed,
                             telemetry=telemetry or Telemetry())
    return engine.startup()


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser("serve.demo")
    p.add_argument("--startup-probe", action="store_true",
                   help="build the executable ladder, print the startup "
                        "timing report as one JSON line, exit (bench.py "
                        "runs this twice in fresh subprocesses for the "
                        "cold/warm startup metric)")
    p.add_argument("--model", default="vgg11")
    p.add_argument("--buckets", default=",".join(map(str, BUCKETS)))
    p.add_argument("--precisions", default="f32",
                   help="comma list from {f32, bf16}")
    p.add_argument("--cache-dir", default=None,
                   help="executable-cache directory (warm start)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--requests", type=int, default=200)
    p.add_argument("--load", type=float, default=20.0,
                   help="offered load, requests/sec (open loop)")
    p.add_argument("--max-wait-ms", type=float, default=5.0)
    args = p.parse_args(argv)

    buckets = parse_buckets(args.buckets)
    precisions = tuple(args.precisions.split(","))
    tel = Telemetry()
    engine = InferenceEngine(args.model, buckets=buckets,
                             precisions=precisions,
                             cache_dir=args.cache_dir, seed=args.seed,
                             telemetry=tel)
    report = engine.startup()
    if args.startup_probe:
        print(json.dumps(report))
        return 0
    stats = run_demo(engine, n_requests=args.requests,
                     offered_rps=args.load, seed=args.seed,
                     max_wait_ms=args.max_wait_ms)
    print(json.dumps({"startup": report, "demo": stats}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
