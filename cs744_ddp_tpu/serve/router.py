"""Least-loaded router over N independent engine replicas.

Routing signal: each replica's ``outstanding_s()`` — predicted seconds
of queued + in-flight work from its ``ServiceModel`` (HLO cost-model
prior corrected by the measured ``serve_service_ms`` EWMA), so the
router is load-aware from the first request and converges to measured
reality.  Ties break by replica index: routing over equal loads is
deterministic.

Failover contract (pinned in tests): when a replica dies mid-flight,
every unfinished request it held — in-flight AND queued — is re-enqueued
on the least-loaded survivor with its original trace id, deadline, and
Future intact; requests that cannot be placed anywhere resolve as
explicit ``error`` replies.  An accepted request always gets exactly one
reply; nothing is silently dropped.
"""

from __future__ import annotations

import threading
from typing import List, Optional

from ..obs import NULL
from .batcher import QueueFull
from .scheduler import Reply, SchedRequest, make_request


class ReplicaRouter:
    """Route requests to the least-loaded live replica.

    ``replicas`` may be ``EngineReplica`` objects or bare ``SLOScheduler``
    instances (anything exposing ``scheduler`` or being one) — tests
    exercise the routing policy against stub schedulers.
    """

    _lock_owned = ("_routed", "_failovers")

    def __init__(self, replicas, *, telemetry=None):
        self.replicas = tuple(replicas)
        if not self.replicas:
            raise ValueError("need at least one replica")
        self.telemetry = telemetry if telemetry is not None else NULL
        self._scheds = tuple(getattr(r, "scheduler", r)
                             for r in self.replicas)
        self._lock = threading.Lock()
        self._routed = 0
        self._failovers = 0
        for sched in self._scheds:
            sched.on_death = self._handle_death

    @property
    def max_batch(self) -> int:
        return self._scheds[0].engine.max_batch

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ReplicaRouter":
        for r in self.replicas:
            r.start()
        return self

    def stop(self) -> None:
        for r in self.replicas:
            r.stop()

    def __enter__(self) -> "ReplicaRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- routing -----------------------------------------------------------

    def _ranked(self, exclude=None) -> List:
        """Live schedulers, least predicted outstanding work first;
        deterministic tiebreak by replica index."""
        live = [s for s in self._scheds
                if s.alive and s is not exclude]
        return sorted(live, key=lambda s: (s.outstanding_s(), s.replica))

    def submit(self, images, labels=None, *, tier: int = 0,
               slo_ms: Optional[float] = None, ctx=None):
        """Admit one request onto the least-loaded replica; falls through
        to the next-loaded on ``QueueFull``.  Raises ``QueueFull`` with
        the smallest retry hint when every replica is saturated, or
        ``RuntimeError`` when none is alive.  ``ctx`` (upstream
        ``TraceContext``) rides the request into dispatch-time spans —
        failover re-placement keeps it, like the trace id."""
        req = make_request(images, labels, tier=tier, slo_ms=slo_ms,
                           max_batch=self.max_batch, ctx=ctx)
        return self._place(req)

    def _place(self, req: SchedRequest, exclude=None):
        tel = self.telemetry
        hint = None
        for sched in self._ranked(exclude=exclude):
            try:
                fut = sched.enqueue(req)
            except QueueFull as e:
                h = getattr(e, "retry_after_ms", 0.0)
                hint = h if hint is None else min(hint, h)
                continue
            except RuntimeError:
                continue          # died between ranking and enqueue
            with self._lock:
                self._routed += 1
            if tel.enabled:
                tel.gauge("replica_outstanding_s",
                          round(sched.outstanding_s(), 6),
                          replica=sched.replica)
            return fut
        if hint is not None:
            raise QueueFull("all replicas at capacity",
                            retry_after_ms=hint)
        raise RuntimeError("no live replicas")

    # -- failover ----------------------------------------------------------

    def _handle_death(self, dead_sched, unfinished, exc) -> None:
        """``on_death`` hook: re-place every unfinished request from the
        dead replica; unplaceable ones resolve as explicit errors."""
        tel = self.telemetry
        if tel.enabled:
            tel.counter("replica_death", replica=dead_sched.replica,
                        error=type(exc).__name__)
        for req in unfinished:
            try:
                self._place(req, exclude=dead_sched)
            except (QueueFull, RuntimeError) as e2:
                if req.future is not None and not req.future.done():
                    req.future.set_result(Reply(
                        status="error", trace=req.trace, tier=req.tier,
                        reason=f"failover failed: {e2}",
                        replica=dead_sched.replica))
                continue
            with self._lock:
                self._failovers += 1
            if tel.enabled:
                tel.counter("serve_failover", tier=req.tier,
                            replica=dead_sched.replica)

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            routed, failovers = self._routed, self._failovers
        return {
            "routed": routed,
            "failovers": failovers,
            "replicas": [{
                "replica": s.replica,
                "alive": s.alive,
                "weights_version": int(getattr(
                    getattr(s, "engine", None), "weights_version", -1)),
                "outstanding_s": round(s.outstanding_s(), 6),
                "svc_ms": {b: round(s.svc.predict(b) * 1e3, 4)
                           for b in s.buckets},
            } for s in self._scheds],
        }
