"""Warm-start executable cache: serialize compiled XLA executables to disk.

Two layers make a restarted server skip compile:

  * the repo-wide persistent XLA compilation cache
    (``utils/compcache.enable_persistent_compilation_cache``) — enabled by
    the engine at startup; it dedupes compiles ACROSS programs but still
    pays lowering + cache lookup per bucket, and only persists compiles
    over its 2 s threshold;
  * this module — the whole compiled executable (``jax.jit(...).lower()
    .compile()``) serialized via ``jax.experimental.serialize_executable``
    and reloaded with zero XLA work, keyed by everything the executable
    depends on (model/abstract-arg digest, bucket, dtype, jax version,
    backend, device kind).  Where the installed jax lacks the API the
    engine silently falls back to compiling (the persistent cache still
    softens that path).

Entries are pickles of ``(payload_bytes, in_tree, out_tree)`` written
atomically (tmp + ``os.replace``) so a killed startup never leaves a torn
entry; a stale or undeserializable entry is treated as a miss and
recompiled over.
"""

from __future__ import annotations

import hashlib
import os
import pickle
from typing import Any, Optional

try:
    from jax.experimental import serialize_executable as _se
except ImportError:                      # pragma: no cover - older jax
    _se = None


def executable_serialization_supported() -> bool:
    """Can this jax serialize/reload compiled executables?"""
    return _se is not None


def cache_key(**fields) -> str:
    """Stable filename for an executable: sha256 over the sorted field
    repr (model digest, bucket, dtype, jax/backend identity)."""
    blob = repr(sorted(fields.items())).encode()
    return hashlib.sha256(blob).hexdigest()[:32]


class ExecutableCache:
    """Directory of serialized executables; ``None`` dir disables it."""

    def __init__(self, cache_dir: Optional[str]):
        self.cache_dir = cache_dir
        self.hits = 0
        self.misses = 0
        if cache_dir is not None:
            os.makedirs(cache_dir, exist_ok=True)

    @property
    def enabled(self) -> bool:
        return self.cache_dir is not None and _se is not None

    def _path(self, key: str) -> str:
        return os.path.join(self.cache_dir, f"exec_{key}.pkl")

    def load(self, key: str) -> Optional[Any]:
        """Deserialize + load the executable for ``key``; None on miss or
        any deserialization failure (a stale entry from another jax/device
        is a miss, not an error)."""
        if not self.enabled:
            return None
        path = self._path(key)
        if not os.path.exists(path):
            self.misses += 1
            return None
        try:
            with open(path, "rb") as f:
                payload, in_tree, out_tree = pickle.load(f)
            loaded = _se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception:
            self.misses += 1
            return None
        self.hits += 1
        return loaded

    def save(self, key: str, compiled) -> bool:
        """Serialize ``compiled`` under ``key``; False when unsupported or
        the executable refuses serialization (nothing breaks — the next
        startup just compiles)."""
        if not self.enabled:
            return False
        try:
            payload, in_tree, out_tree = _se.serialize(compiled)
            blob = pickle.dumps((payload, in_tree, out_tree))
        except Exception:
            return False
        path = self._path(key)
        tmp = f"{path}.{os.getpid()}.tmp"
        try:
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return True

    def stats(self) -> dict:
        return {"dir": self.cache_dir, "supported": _se is not None,
                "hits": self.hits, "misses": self.misses}
