"""Socket front-end: length-prefixed binary protocol over TCP.

Wire format (all little-endian, u32 frame-length prefix per message):

* request  = ``<IBBdH`` header (req_id u32, msg u8 = 1, tier u8,
  slo_ms f64 — <= 0 means no deadline, n u16) + n x 3072 raw u8 bytes
  (n CIFAR images, HWC 32x32x3).
* reply    = ``<IBBQdddiH`` header (req_id u32, status u8, reason u8,
  trace u64, retry_after_ms f64, queue_wait_ms f64, service_ms f64,
  model_version i32 — the engine weights version that served the
  request (publish/ hot-swap A/B pin), -1 when it never reached a
  dispatch, n u16) + n x 10 f32 logits when status is ok/late.

Statuses: 0 ok, 1 late (served past deadline), 2 shed, 3 overload
(rejected at admission — ``retry_after_ms`` carries the micro-batcher's
backpressure hint, the satellite fix), 4 error.  Every request gets
exactly one reply; replies are written as each Future resolves, so they
can return OUT OF ORDER — clients match on ``req_id``.

``ServingFrontend`` serves any backend exposing
``submit(images, labels=None, *, tier, slo_ms) -> Future[Reply]`` and
raising ``QueueFull`` — an ``SLOScheduler``, a ``ReplicaRouter``, or a
stub.  ``FrontendClient`` (socket) and ``LoopbackClient`` (in-process,
same reply dicts) are the two client shapes tests/bench drive.
"""

from __future__ import annotations

import socket
import struct
import threading
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..obs import NULL
from .batcher import QueueFull

IMAGE_BYTES = 32 * 32 * 3
MSG_INFER = 1

_LEN = struct.Struct("<I")
_REQ = struct.Struct("<IBBdH")
_REP = struct.Struct("<IBBQdddiH")

STATUS_CODES = {"ok": 0, "late": 1, "shed": 2, "overload": 3, "error": 4}
STATUS_NAMES = {v: k for k, v in STATUS_CODES.items()}
REASON_CODES = {"": 0, "deadline": 1, "predicted_miss": 2, "queue_full": 3,
                "internal": 4}
REASON_NAMES = {v: k for k, v in REASON_CODES.items()}

MAX_FRAME = _REQ.size + 65535 * IMAGE_BYTES


# -- codec ------------------------------------------------------------------


def encode_request(req_id: int, images: np.ndarray, *, tier: int = 0,
                   slo_ms: Optional[float] = None) -> bytes:
    images = np.ascontiguousarray(images, np.uint8)
    n = int(images.shape[0])
    if not 0 < n <= 65535:
        raise ValueError(f"bad request size {n}")
    slo = -1.0 if slo_ms is None else float(slo_ms)
    return _REQ.pack(req_id & 0xFFFFFFFF, MSG_INFER, int(tier) & 0xFF,
                     slo, n) + images.tobytes()


def decode_request(payload: bytes
                   ) -> Tuple[int, np.ndarray, int, Optional[float]]:
    if len(payload) < _REQ.size:
        raise ValueError(f"short request frame ({len(payload)} B)")
    req_id, msg, tier, slo, n = _REQ.unpack_from(payload)
    if msg != MSG_INFER:
        raise ValueError(f"unknown message type {msg}")
    body = payload[_REQ.size:]
    if len(body) != n * IMAGE_BYTES:
        raise ValueError(f"request body {len(body)} B != {n} images")
    images = np.frombuffer(body, np.uint8).reshape(n, 32, 32, 3)
    return req_id, images, tier, (None if slo <= 0 else slo)


def encode_reply(req_id: int, reply) -> bytes:
    """``reply`` is a ``scheduler.Reply`` or an equivalent dict."""
    get = reply.get if isinstance(reply, dict) else \
        lambda k, d=None: getattr(reply, k, d)
    status = STATUS_CODES[get("status")]
    logits = get("logits")
    blob = b""
    n = 0
    if logits is not None and status in (0, 1):
        logits = np.ascontiguousarray(logits, np.float32)
        n = int(logits.shape[0])
        blob = logits.tobytes()
    reason = get("reason") or ""
    rcode = REASON_CODES.get(reason.split(":")[0],
                             REASON_CODES["internal"] if reason else 0)
    mv = get("model_version")
    return _REP.pack(req_id & 0xFFFFFFFF, status, rcode,
                     int(get("trace") or 0), float(get("retry_after_ms") or 0.0),
                     float(get("queue_wait_ms") or 0.0),
                     float(get("service_ms") or 0.0),
                     -1 if mv is None else int(mv), n) + blob


def decode_reply(payload: bytes) -> dict:
    if len(payload) < _REP.size:
        raise ValueError(f"short reply frame ({len(payload)} B)")
    req_id, status, rcode, trace, retry, qw, svc, mv, n = \
        _REP.unpack_from(payload)
    body = payload[_REP.size:]
    logits = None
    if n:
        if len(body) != n * 40:
            raise ValueError(f"reply body {len(body)} B != {n} rows")
        logits = np.frombuffer(body, np.float32).reshape(n, 10).copy()
    return {"req_id": req_id, "status": STATUS_NAMES.get(status, "error"),
            "reason": REASON_NAMES.get(rcode, "internal"), "trace": trace,
            "retry_after_ms": retry, "queue_wait_ms": qw, "service_ms": svc,
            "model_version": mv, "logits": logits}


def reply_to_dict(reply) -> dict:
    """Normalize a ``scheduler.Reply`` to the client-side reply dict."""
    return {"req_id": None, "status": reply.status, "reason": reply.reason,
            "trace": reply.trace, "retry_after_ms": reply.retry_after_ms,
            "queue_wait_ms": reply.queue_wait_ms,
            "service_ms": reply.service_ms,
            "model_version": getattr(reply, "model_version", -1),
            "logits": reply.logits}


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def read_frame(sock: socket.socket) -> Optional[bytes]:
    head = _recv_exact(sock, _LEN.size)
    if head is None:
        return None
    (length,) = _LEN.unpack(head)
    if length > MAX_FRAME:
        raise ValueError(f"frame of {length} B exceeds {MAX_FRAME}")
    return _recv_exact(sock, length)


def write_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


# -- server -----------------------------------------------------------------


class ServingFrontend:
    """Threaded acceptor feeding the admission queue.

    One thread per connection; replies are written from Future
    done-callbacks under a per-connection send lock (the scheduler's
    worker resolves Futures out of admission order).  ``QueueFull`` at
    admission becomes an overload reply carrying the backpressure
    retry-after hint; any other admission failure becomes an explicit
    error reply — the no-silent-drop contract extends to the wire.
    """

    _lock_owned = ("_conns", "_threads", "_running")

    def __init__(self, backend, *, host: str = "127.0.0.1", port: int = 0,
                 telemetry=None):
        self.backend = backend
        self.telemetry = telemetry if telemetry is not None else NULL
        self._host = host
        self._port = port
        self._listener: Optional[socket.socket] = None
        self._acceptor: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._conns: List[socket.socket] = []
        self._threads: List[threading.Thread] = []
        self._running = False

    @property
    def address(self) -> Tuple[str, int]:
        if self._listener is None:
            raise RuntimeError("frontend not started")
        return self._listener.getsockname()[:2]

    def start(self) -> "ServingFrontend":
        if self._listener is not None:
            raise RuntimeError("frontend already started")
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((self._host, self._port))
        ls.listen(64)
        self._listener = ls
        with self._lock:
            self._running = True
        self._acceptor = threading.Thread(target=self._accept_loop,
                                          name="serve-accept", daemon=True)
        self._acceptor.start()
        return self

    def stop(self) -> None:
        with self._lock:
            self._running = False
            conns = list(self._conns)
            threads = list(self._threads)
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        if self._acceptor is not None:
            self._acceptor.join(timeout=5.0)
            self._acceptor = None
        for t in threads:
            t.join(timeout=5.0)
        with self._lock:
            self._conns = []
            self._threads = []
        self._listener = None

    def __enter__(self) -> "ServingFrontend":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return           # listener closed by stop()
            with self._lock:
                if not self._running:
                    conn.close()
                    return
                t = threading.Thread(target=self._serve_conn, args=(conn,),
                                     name="serve-conn", daemon=True)
                self._conns.append(conn)
                self._threads.append(t)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        tel = self.telemetry
        send_lock = threading.Lock()
        try:
            while True:
                try:
                    payload = read_frame(conn)
                except (OSError, ValueError):
                    return
                if payload is None:
                    return
                try:
                    req_id, images, tier, slo_ms = decode_request(payload)
                except ValueError:
                    return       # malformed frame: drop the connection
                try:
                    fut = self.backend.submit(images, tier=tier,
                                              slo_ms=slo_ms)
                except QueueFull as e:
                    if tel.enabled:
                        tel.counter("frontend_overload", tier=tier)
                    self._send(conn, send_lock, encode_reply(req_id, {
                        "status": "overload", "reason": "queue_full",
                        "retry_after_ms": getattr(e, "retry_after_ms", 0.0),
                    }))
                    continue
                except (RuntimeError, ValueError) as e:
                    self._send(conn, send_lock, encode_reply(req_id, {
                        "status": "error", "reason": "internal",
                    }))
                    del e
                    continue
                if tel.enabled:
                    tel.counter("frontend_accepted", tier=tier)
                fut.add_done_callback(
                    lambda f, rid=req_id, lk=send_lock, c=conn:
                    self._on_reply(c, lk, rid, f))
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _on_reply(self, conn, send_lock, req_id: int, fut) -> None:
        try:
            reply = fut.result()
        except Exception:
            reply = {"status": "error", "reason": "internal"}
        self._send(conn, send_lock, encode_reply(req_id, reply))

    @staticmethod
    def _send(conn, send_lock, payload: bytes) -> None:
        try:
            with send_lock:
                write_frame(conn, payload)
        except OSError:
            pass                 # client went away; reply is undeliverable


# -- clients ----------------------------------------------------------------


class FrontendClient:
    """Socket client: pipelined submits, replies matched by ``req_id``
    from a reader thread; each submit returns a Future of a reply dict."""

    _lock_owned = ("_futs", "_next_id")

    def __init__(self, address: Tuple[str, int], *, timeout: float = 60.0):
        self.timeout = timeout
        self._sock = socket.create_connection(address, timeout=timeout)
        self._lock = threading.Lock()
        self._futs: Dict[int, Future] = {}
        self._next_id = 1
        self._reader = threading.Thread(target=self._read_loop,
                                        name="serve-client", daemon=True)
        self._reader.start()

    def submit(self, images, *, tier: int = 0,
               slo_ms: Optional[float] = None) -> Future:
        fut = Future()
        with self._lock:
            req_id = self._next_id
            self._next_id += 1
            self._futs[req_id] = fut
        try:
            write_frame(self._sock, encode_request(req_id, images,
                                                   tier=tier, slo_ms=slo_ms))
        except OSError as e:
            with self._lock:
                self._futs.pop(req_id, None)
            raise ConnectionError(f"frontend connection lost: {e}") from e
        return fut

    def request(self, images, *, tier: int = 0,
                slo_ms: Optional[float] = None) -> dict:
        return self.submit(images, tier=tier, slo_ms=slo_ms) \
            .result(timeout=self.timeout)

    def _read_loop(self) -> None:
        while True:
            try:
                payload = read_frame(self._sock)
            except (OSError, ValueError):
                payload = None
            if payload is None:
                break
            try:
                reply = decode_reply(payload)
            except ValueError:
                break
            with self._lock:
                fut = self._futs.pop(reply["req_id"], None)
            if fut is not None and not fut.done():
                fut.set_result(reply)
        with self._lock:
            dangling = list(self._futs.values())
            self._futs = {}
        for fut in dangling:
            if not fut.done():
                fut.set_result({"req_id": None, "status": "error",
                                "reason": "internal", "trace": 0,
                                "retry_after_ms": 0.0, "queue_wait_ms": 0.0,
                                "service_ms": 0.0, "model_version": -1,
                                "logits": None})

    def close(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._reader.join(timeout=5.0)

    def __enter__(self) -> "FrontendClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class LoopbackClient:
    """In-process client with the same submit/reply-dict surface as
    ``FrontendClient`` — what bench and the demo replay drive when no
    socket is wanted.  Overload is returned as a reply dict (like the
    wire does), not raised."""

    def __init__(self, backend):
        self.backend = backend

    def submit(self, images, *, tier: int = 0,
               slo_ms: Optional[float] = None) -> Future:
        try:
            fut = self.backend.submit(images, tier=tier, slo_ms=slo_ms)
        except QueueFull as e:
            done = Future()
            done.set_result({"req_id": None, "status": "overload",
                             "reason": "queue_full", "trace": 0,
                             "retry_after_ms": getattr(e, "retry_after_ms",
                                                       0.0),
                             "queue_wait_ms": 0.0, "service_ms": 0.0,
                             "model_version": -1, "logits": None})
            return done
        except (RuntimeError, ValueError) as e:
            done = Future()
            done.set_result({"req_id": None, "status": "error",
                             "reason": f"internal: {e}", "trace": 0,
                             "retry_after_ms": 0.0, "queue_wait_ms": 0.0,
                             "service_ms": 0.0, "model_version": -1,
                             "logits": None})
            return done
        out = Future()
        fut.add_done_callback(
            lambda f: out.set_result(reply_to_dict(f.result())))
        return out

    def request(self, images, *, tier: int = 0,
                slo_ms: Optional[float] = None) -> dict:
        return self.submit(images, tier=tier, slo_ms=slo_ms).result()

    def close(self) -> None:
        pass
