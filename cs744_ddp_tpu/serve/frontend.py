"""Socket front-end: length-prefixed binary protocol over TCP.

Wire format (all little-endian, u32 frame-length prefix per message):

* request  = ``<IBBdH`` header (req_id u32, msg u8 = 1, tier u8,
  slo_ms f64 — <= 0 means no deadline, n u16) + n x 3072 raw u8 bytes
  (n CIFAR images, HWC 32x32x3).
* reply    = ``<IBBQdddiH`` header (req_id u32, status u8, reason u8,
  trace u64, retry_after_ms f64, queue_wait_ms f64, service_ms f64,
  model_version i32 — the engine weights version that served the
  request (publish/ hot-swap A/B pin), -1 when it never reached a
  dispatch, n u16) + n x 10 f32 logits when status is ok/late.

Both frames may carry an OPTIONAL TRAILING EXTENSION BLOCK (round 12,
``obs/tracing.py``: magic+version byte then TLV fields, unknown tags
skipped by length).  Requests use it for the distributed
``TraceContext``; replies for the server's recv/send timestamps (the
client side of clock-skew estimation).  Encoding without a context is
byte-identical to the pre-round-12 format, and the decoders accept
extension-free frames — old and new peers mix freely in either
direction; trailing bytes that are NOT a versioned extension block
still fail decode (torn frames must not pass silently).

Statuses: 0 ok, 1 late (served past deadline), 2 shed, 3 overload
(rejected at admission — ``retry_after_ms`` carries the micro-batcher's
backpressure hint, the satellite fix), 4 error.  Every request gets
exactly one reply; replies are written as each Future resolves, so they
can return OUT OF ORDER — clients match on ``req_id``.

``ServingFrontend`` serves any backend exposing
``submit(images, labels=None, *, tier, slo_ms) -> Future[Reply]`` and
raising ``QueueFull`` — an ``SLOScheduler``, a ``ReplicaRouter``, or a
stub.  ``FrontendClient`` (socket) and ``LoopbackClient`` (in-process,
same reply dicts) are the two client shapes tests/bench drive.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..obs import NULL
from ..obs.tracing import (TAG_SERVER_TIMES, TAG_TRACE, TraceContext,
                           pack_ext, pack_server_times, pack_trace,
                           unpack_ext_ex, unpack_server_times, unpack_trace)
from .batcher import QueueFull

IMAGE_BYTES = 32 * 32 * 3
MSG_INFER = 1

_LEN = struct.Struct("<I")
_REQ = struct.Struct("<IBBdH")
_REP = struct.Struct("<IBBQdddiH")

STATUS_CODES = {"ok": 0, "late": 1, "shed": 2, "overload": 3, "error": 4}
STATUS_NAMES = {v: k for k, v in STATUS_CODES.items()}
REASON_CODES = {"": 0, "deadline": 1, "predicted_miss": 2, "queue_full": 3,
                "internal": 4}
REASON_NAMES = {v: k for k, v in REASON_CODES.items()}

# 4 KiB of slack past the fixed layout for trailing extension blocks.
MAX_FRAME = _REQ.size + 65535 * IMAGE_BYTES + 4096


# -- codec ------------------------------------------------------------------


def _split_ext(body: bytes, fixed: int, what: str,
               telemetry=None) -> Tuple[bytes, dict]:
    """Split a frame body into (fixed-layout bytes, decoded extension
    fields).  Trailing bytes must be a versioned extension block
    (``unpack_ext_ex`` magic-gates them) — anything else is a torn frame
    and still fails decode, exactly as the pre-extension codec did.
    Unknown tags and dropped torn fields are counted into the
    ``wire_ext_skipped`` counter when a telemetry sink is supplied —
    a newer peer's fields silently falling on the floor is exactly the
    cross-version drift the operator needs to see."""
    if len(body) < fixed:
        raise ValueError(f"{what} body {len(body)} B < {fixed} B")
    tail = body[fixed:]
    if not tail:
        return body, {}
    fields, skipped, torn = unpack_ext_ex(tail)
    if not fields:
        raise ValueError(f"{what} body {len(body)} B != {fixed} B "
                         "(trailing bytes are not an extension block)")
    if (skipped or torn) and telemetry is not None \
            and getattr(telemetry, "enabled", False):
        telemetry.counter("wire_ext_skipped", skipped + torn,
                          unknown=skipped, torn=torn, frame=what)
    return body[:fixed], fields


def encode_request(req_id: int, images: np.ndarray, *, tier: int = 0,
                   slo_ms: Optional[float] = None,
                   ctx: Optional[TraceContext] = None) -> bytes:
    images = np.ascontiguousarray(images, np.uint8)
    n = int(images.shape[0])
    if not 0 < n <= 65535:
        raise ValueError(f"bad request size {n}")
    slo = -1.0 if slo_ms is None else float(slo_ms)
    ext = b"" if ctx is None else pack_ext({TAG_TRACE: pack_trace(ctx)})
    return _REQ.pack(req_id & 0xFFFFFFFF, MSG_INFER, int(tier) & 0xFF,
                     slo, n) + images.tobytes() + ext


def decode_request_ex(payload: bytes, telemetry=None
                      ) -> Tuple[int, np.ndarray, int, Optional[float],
                                 Optional[TraceContext]]:
    """Decode a request frame -> (req_id, images, tier, slo_ms, ctx).
    ``ctx`` is None for extension-free (old-client) frames."""
    if len(payload) < _REQ.size:
        raise ValueError(f"short request frame ({len(payload)} B)")
    req_id, msg, tier, slo, n = _REQ.unpack_from(payload)
    if msg != MSG_INFER:
        raise ValueError(f"unknown message type {msg}")
    body, fields = _split_ext(payload[_REQ.size:], n * IMAGE_BYTES,
                              "request", telemetry)
    images = np.frombuffer(body, np.uint8).reshape(n, 32, 32, 3)
    ctx = unpack_trace(fields[TAG_TRACE]) if TAG_TRACE in fields else None
    return req_id, images, tier, (None if slo <= 0 else slo), ctx


def decode_request(payload: bytes
                   ) -> Tuple[int, np.ndarray, int, Optional[float]]:
    """The pre-round-12 4-tuple surface (extension fields tolerated and
    dropped) — existing callers keep working unchanged."""
    req_id, images, tier, slo_ms, _ctx = decode_request_ex(payload)
    return req_id, images, tier, slo_ms


def encode_reply(req_id: int, reply, *, t_recv: Optional[float] = None,
                 t_send: Optional[float] = None) -> bytes:
    """``reply`` is a ``scheduler.Reply`` or an equivalent dict."""
    get = reply.get if isinstance(reply, dict) else \
        lambda k, d=None: getattr(reply, k, d)
    status = STATUS_CODES[get("status")]
    logits = get("logits")
    blob = b""
    n = 0
    if logits is not None and status in (0, 1):
        logits = np.ascontiguousarray(logits, np.float32)
        n = int(logits.shape[0])
        blob = logits.tobytes()
    reason = get("reason") or ""
    rcode = REASON_CODES.get(reason.split(":")[0],
                             REASON_CODES["internal"] if reason else 0)
    mv = get("model_version")
    ext = b"" if t_recv is None or t_send is None else \
        pack_ext({TAG_SERVER_TIMES: pack_server_times(t_recv, t_send)})
    return _REP.pack(req_id & 0xFFFFFFFF, status, rcode,
                     int(get("trace") or 0), float(get("retry_after_ms") or 0.0),
                     float(get("queue_wait_ms") or 0.0),
                     float(get("service_ms") or 0.0),
                     -1 if mv is None else int(mv), n) + blob + ext


def decode_reply(payload: bytes, telemetry=None) -> dict:
    if len(payload) < _REP.size:
        raise ValueError(f"short reply frame ({len(payload)} B)")
    req_id, status, rcode, trace, retry, qw, svc, mv, n = \
        _REP.unpack_from(payload)
    body, fields = _split_ext(payload[_REP.size:], n * 40, "reply",
                              telemetry)
    logits = None
    if n:
        logits = np.frombuffer(body, np.float32).reshape(n, 10).copy()
    rep = {"req_id": req_id, "status": STATUS_NAMES.get(status, "error"),
           "reason": REASON_NAMES.get(rcode, "internal"), "trace": trace,
           "retry_after_ms": retry, "queue_wait_ms": qw, "service_ms": svc,
           "model_version": mv, "logits": logits}
    if TAG_SERVER_TIMES in fields:
        times = unpack_server_times(fields[TAG_SERVER_TIMES])
        if times is not None:
            rep["t_recv"], rep["t_send"] = times
    return rep


def reply_to_dict(reply) -> dict:
    """Normalize a ``scheduler.Reply`` to the client-side reply dict."""
    return {"req_id": None, "status": reply.status, "reason": reply.reason,
            "trace": reply.trace, "retry_after_ms": reply.retry_after_ms,
            "queue_wait_ms": reply.queue_wait_ms,
            "service_ms": reply.service_ms,
            "model_version": getattr(reply, "model_version", -1),
            "logits": reply.logits}


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def read_frame(sock: socket.socket) -> Optional[bytes]:
    head = _recv_exact(sock, _LEN.size)
    if head is None:
        return None
    (length,) = _LEN.unpack(head)
    if length > MAX_FRAME:
        raise ValueError(f"frame of {length} B exceeds {MAX_FRAME}")
    return _recv_exact(sock, length)


def write_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


# -- server -----------------------------------------------------------------


class ServingFrontend:
    """Threaded acceptor feeding the admission queue.

    One thread per connection; replies are written from Future
    done-callbacks under a per-connection send lock (the scheduler's
    worker resolves Futures out of admission order).  ``QueueFull`` at
    admission becomes an overload reply carrying the backpressure
    retry-after hint; any other admission failure becomes an explicit
    error reply — the no-silent-drop contract extends to the wire.
    """

    _lock_owned = ("_conns", "_threads", "_running")

    def __init__(self, backend, *, host: str = "127.0.0.1", port: int = 0,
                 telemetry=None):
        self.backend = backend
        self.telemetry = telemetry if telemetry is not None else NULL
        self._host = host
        self._port = port
        self._listener: Optional[socket.socket] = None
        self._acceptor: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._conns: List[socket.socket] = []
        self._threads: List[threading.Thread] = []
        self._running = False

    @property
    def address(self) -> Tuple[str, int]:
        if self._listener is None:
            raise RuntimeError("frontend not started")
        return self._listener.getsockname()[:2]

    def start(self) -> "ServingFrontend":
        if self._listener is not None:
            raise RuntimeError("frontend already started")
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((self._host, self._port))
        ls.listen(64)
        self._listener = ls
        with self._lock:
            self._running = True
        self._acceptor = threading.Thread(target=self._accept_loop,
                                          name="serve-accept", daemon=True)
        self._acceptor.start()
        return self

    def stop(self) -> None:
        with self._lock:
            self._running = False
            conns = list(self._conns)
            threads = list(self._threads)
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        if self._acceptor is not None:
            self._acceptor.join(timeout=5.0)
            self._acceptor = None
        for t in threads:
            t.join(timeout=5.0)
        with self._lock:
            self._conns = []
            self._threads = []
        self._listener = None

    def __enter__(self) -> "ServingFrontend":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return           # listener closed by stop()
            with self._lock:
                if not self._running:
                    conn.close()
                    return
                t = threading.Thread(target=self._serve_conn, args=(conn,),
                                     name="serve-conn", daemon=True)
                self._conns.append(conn)
                self._threads.append(t)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        tel = self.telemetry
        send_lock = threading.Lock()
        try:
            while True:
                try:
                    payload = read_frame(conn)
                except (OSError, ValueError):
                    return
                if payload is None:
                    return
                t_recv = time.time()
                try:
                    req_id, images, tier, slo_ms, ctx = \
                        decode_request_ex(payload, tel)
                except ValueError:
                    return       # malformed frame: drop the connection
                # The frontend hop's own context: child of the client's
                # when the request carried one, else a fresh root (old
                # clients stay traceable server-side).  NULL recorder ->
                # no context, no allocations.
                sctx = None
                if tel.enabled:
                    sctx = ctx.child("frontend") if ctx is not None \
                        else TraceContext.new_root("frontend")
                    tel.span_event("wire_decode", t_recv,
                                   time.time() - t_recv,
                                   **sctx.child("frontend").attrs())
                try:
                    if sctx is not None:
                        fut = self.backend.submit(images, tier=tier,
                                                  slo_ms=slo_ms, ctx=sctx)
                    else:
                        fut = self.backend.submit(images, tier=tier,
                                                  slo_ms=slo_ms)
                except QueueFull as e:
                    if tel.enabled:
                        tel.counter("frontend_overload", tier=tier)
                    self._reply_now(conn, send_lock, req_id, {
                        "status": "overload", "reason": "queue_full",
                        "retry_after_ms": getattr(e, "retry_after_ms", 0.0),
                    }, t_recv=t_recv, ctx=sctx)
                    continue
                except (RuntimeError, ValueError) as e:
                    self._reply_now(conn, send_lock, req_id, {
                        "status": "error", "reason": "internal",
                    }, t_recv=t_recv, ctx=sctx)
                    del e
                    continue
                if tel.enabled:
                    tel.counter("frontend_accepted", tier=tier)
                fut.add_done_callback(
                    lambda f, rid=req_id, lk=send_lock, c=conn, tr=t_recv,
                    sc=sctx: self._on_reply(c, lk, rid, f, t_recv=tr,
                                            ctx=sc))
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _on_reply(self, conn, send_lock, req_id: int, fut, *,
                  t_recv: Optional[float] = None, ctx=None) -> None:
        try:
            reply = fut.result()
        except Exception:
            reply = {"status": "error", "reason": "internal"}
        self._reply_now(conn, send_lock, req_id, reply,
                        t_recv=t_recv, ctx=ctx)

    def _reply_now(self, conn, send_lock, req_id: int, reply, *,
                   t_recv: Optional[float] = None, ctx=None) -> None:
        """Encode + send one reply; when traced, stamp the server's
        recv/send window into the wire extension AND emit the
        ``frontend_request`` span the skew estimator matches against
        the client's ``trace_client`` span."""
        tel = self.telemetry
        if ctx is None or not tel.enabled:
            self._send(conn, send_lock, encode_reply(req_id, reply))
            return
        t0 = time.time()
        payload = encode_reply(req_id, reply, t_recv=t_recv, t_send=t0)
        tel.span_event("reply_encode", t0, time.time() - t0,
                       **ctx.child("frontend").attrs())
        self._send(conn, send_lock, payload)
        get = reply.get if isinstance(reply, dict) else \
            lambda k, d=None: getattr(reply, k, d)
        attrs = ctx.attrs()
        if get("trace"):
            attrs["trace"] = get("trace")
        attrs["status"] = get("status")
        tel.span_event("frontend_request", t_recv,
                       time.time() - t_recv, **attrs)

    @staticmethod
    def _send(conn, send_lock, payload: bytes) -> None:
        try:
            with send_lock:
                write_frame(conn, payload)
        except OSError:
            pass                 # client went away; reply is undeliverable


# -- clients ----------------------------------------------------------------


def _trace_client_reply(tel, ctx: TraceContext, t1: float, fut) -> None:
    """Future done-callback: emit the client round-trip span (t1..t4 on
    the CLIENT clock) carrying the trace context plus whatever join keys
    the reply brought back (batcher trace id, server recv/send times)."""
    try:
        rep = fut.result()
    except Exception:
        rep = None
    t4 = time.time()
    attrs = ctx.attrs()
    if isinstance(rep, dict):
        if rep.get("trace"):
            attrs["trace"] = rep["trace"]
        if "t_recv" in rep:
            attrs["server_t_recv"] = rep["t_recv"]
            attrs["server_t_send"] = rep["t_send"]
        attrs["status"] = rep.get("status")
    tel.span_event("trace_client", t1, t4 - t1, **attrs)


class FrontendClient:
    """Socket client: pipelined submits, replies matched by ``req_id``
    from a reader thread; each submit returns a Future of a reply dict."""

    _lock_owned = ("_futs", "_next_id")

    def __init__(self, address: Tuple[str, int], *, timeout: float = 60.0,
                 telemetry=None):
        self.timeout = timeout
        self.telemetry = telemetry if telemetry is not None else NULL
        self._sock = socket.create_connection(address, timeout=timeout)
        self._lock = threading.Lock()
        self._futs: Dict[int, Future] = {}
        self._next_id = 1
        self._reader = threading.Thread(target=self._read_loop,
                                        name="serve-client", daemon=True)
        self._reader.start()

    def submit(self, images, *, tier: int = 0,
               slo_ms: Optional[float] = None) -> Future:
        fut = Future()
        tel = self.telemetry
        # A telemetry-carrying client is a TRACING client: it mints the
        # root context every downstream hop parents under and records
        # the t1..t4 round-trip the skew estimator pairs with the
        # server's frontend_request window.
        ctx = TraceContext.new_root("client") if tel.enabled else None
        with self._lock:
            req_id = self._next_id
            self._next_id += 1
            self._futs[req_id] = fut
        t1 = time.time()
        try:
            write_frame(self._sock, encode_request(req_id, images,
                                                   tier=tier, slo_ms=slo_ms,
                                                   ctx=ctx))
        except OSError as e:
            with self._lock:
                self._futs.pop(req_id, None)
            raise ConnectionError(f"frontend connection lost: {e}") from e
        if ctx is not None:
            fut.add_done_callback(
                lambda f, c=ctx, t0=t1: _trace_client_reply(tel, c, t0, f))
        return fut

    def request(self, images, *, tier: int = 0,
                slo_ms: Optional[float] = None) -> dict:
        return self.submit(images, tier=tier, slo_ms=slo_ms) \
            .result(timeout=self.timeout)

    def _read_loop(self) -> None:
        while True:
            try:
                payload = read_frame(self._sock)
            except (OSError, ValueError):
                payload = None
            if payload is None:
                break
            try:
                reply = decode_reply(payload, self.telemetry)
            except ValueError:
                break
            with self._lock:
                fut = self._futs.pop(reply["req_id"], None)
            if fut is not None and not fut.done():
                fut.set_result(reply)
        with self._lock:
            dangling = list(self._futs.values())
            self._futs = {}
        for fut in dangling:
            if not fut.done():
                fut.set_result({"req_id": None, "status": "error",
                                "reason": "internal", "trace": 0,
                                "retry_after_ms": 0.0, "queue_wait_ms": 0.0,
                                "service_ms": 0.0, "model_version": -1,
                                "logits": None})

    def close(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._reader.join(timeout=5.0)

    def __enter__(self) -> "FrontendClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class LoopbackClient:
    """In-process client with the same submit/reply-dict surface as
    ``FrontendClient`` — what bench and the demo replay drive when no
    socket is wanted.  Overload is returned as a reply dict (like the
    wire does), not raised."""

    def __init__(self, backend, *, telemetry=None):
        self.backend = backend
        self.telemetry = telemetry if telemetry is not None else NULL

    def submit(self, images, *, tier: int = 0,
               slo_ms: Optional[float] = None) -> Future:
        tel = self.telemetry
        ctx = TraceContext.new_root("client") if tel.enabled else None
        t1 = time.time()
        try:
            if ctx is not None:
                fut = self.backend.submit(images, tier=tier, slo_ms=slo_ms,
                                          ctx=ctx.child("frontend"))
            else:
                fut = self.backend.submit(images, tier=tier, slo_ms=slo_ms)
        except QueueFull as e:
            done = Future()
            done.set_result({"req_id": None, "status": "overload",
                             "reason": "queue_full", "trace": 0,
                             "retry_after_ms": getattr(e, "retry_after_ms",
                                                       0.0),
                             "queue_wait_ms": 0.0, "service_ms": 0.0,
                             "model_version": -1, "logits": None})
            return done
        except (RuntimeError, ValueError) as e:
            done = Future()
            done.set_result({"req_id": None, "status": "error",
                             "reason": f"internal: {e}", "trace": 0,
                             "retry_after_ms": 0.0, "queue_wait_ms": 0.0,
                             "service_ms": 0.0, "model_version": -1,
                             "logits": None})
            return done
        out = Future()
        fut.add_done_callback(
            lambda f: out.set_result(reply_to_dict(f.result())))
        if ctx is not None:
            out.add_done_callback(
                lambda f, c=ctx, t0=t1: _trace_client_reply(tel, c, t0, f))
        return out

    def request(self, images, *, tier: int = 0,
                slo_ms: Optional[float] = None) -> dict:
        return self.submit(images, tier=tier, slo_ms=slo_ms).result()

    def close(self) -> None:
        pass
