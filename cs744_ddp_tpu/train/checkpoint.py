"""Checkpoint/resume for training state (orbax-backed).

The reference has NO checkpointing (no torch.save/load anywhere — SURVEY.md
§5: training state lives only in memory for the duration of a run), so this
subsystem is beyond-parity: it exists because a framework, unlike coursework
scripts, must survive preemption — the normal operating condition on TPU
pods.

Resume is EXACT: the per-epoch PRNG key is ``fold_in(seed, epoch)`` and the
reference's sampler never reshuffles across epochs (SURVEY.md C6), so
training epochs [0..k) then restoring and training [k..n) is bitwise
identical to training [0..n) in one run (pinned by
tests/test_checkpoint.py).  State on disk is the full TrainState pytree —
params, BatchNorm running stats, SGD momentum — saved per completed epoch;
orbax handles sharded/multi-host arrays natively.
"""

from __future__ import annotations

import json
import os
from typing import Optional, Tuple

import jax

import orbax.checkpoint as ocp

from .step import TrainState

# Bumped whenever the on-disk TrainState pytree STRUCTURE changes (e.g. an
# optimizer-state field added/removed): old checkpoints cannot be restored
# across such changes, and without this stamp the failure is orbax's opaque
# structure error (or a config-digest mismatch that doesn't say WHY).
# History: 1 = SGDState carried a step counter; 2 = it doesn't;
# 3 = SGDState gained ``comm`` (gradient-compression error-feedback
# residuals / PowerSGD factors, stacked per worker — parallel/strategies).
STATE_FORMAT_VERSION = 3
# The structure every pre-stamp directory holds (the 1 -> 2 change predates
# the stamp's introduction) — what a missing stamp migrates to.
_UNSTAMPED_DIR_VERSION = 2

def _v2_structure_is_current(config: Optional[dict]) -> bool:
    """Whether a version-2 checkpoint holds this build's structure anyway.

    The 2 -> 3 bump added ``SGDState.comm`` — which is ``None`` (an empty
    pytree) for every stateless strategy, so a v2 save from such a run is
    leaf-for-leaf the structure this build stores and restores.  Refusing
    it would strand every pre-compression checkpoint for no reason; only
    the stateful tiers (compress-*/powersgd), which post-date version 2,
    genuinely need the new structure."""
    from ..parallel.strategies import STRATEGIES
    strat = STRATEGIES.get(str((config or {}).get("strategy", "")).lower())
    return strat is not None and not getattr(strat, "stateful", False)


# Mid-epoch (emergency) checkpoints are keyed by one orbax step integer
# encoding (epoch, step-within-epoch); an epoch never holds this many
# batches, so the encoding is collision-free and order-preserving.
_MID_KEY_BASE = 10 ** 6


def _atomic_write_json(path: str, obj) -> None:
    """Complete-or-absent JSON write (tmp + rename); a preemption signal
    arriving mid-write must never leave a torn metadata file."""
    tmp = f"{path}.{os.getpid()}.tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(obj, f)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


# The identity keys a published weight bundle carries (publish/): enough
# for the serving side to refuse a bundle from the wrong run/architecture,
# none of the training-only knobs (lr, augment, ...) that don't affect
# what the weights ARE.
_PUBLISH_FINGERPRINT_KEYS = ("model", "strategy", "precision", "seed",
                             "global_batch", "state_digest")


def publish_fingerprint(config: dict) -> dict:
    """Model/config identity stamped into published weight bundles —
    the same fields the checkpoint config guard validates, plus the
    state-format stamp."""
    fp = {k: config[k] for k in _PUBLISH_FINGERPRINT_KEYS if k in config}
    fp.setdefault("state_format_version", STATE_FORMAT_VERSION)
    return fp


# Config keys an ELASTIC resume is allowed to change: the whole point of
# the elastic layer is resuming at a different world size (and, under weak
# scaling, a rescaled global batch) — see cs744_ddp_tpu/elastic/.
_ELASTIC_FREE_KEYS = ("world", "global_batch")


def read_epoch_meta(directory: str) -> Optional[dict]:
    """The elastic metadata sidecar of the latest EPOCH save (world,
    global_batch, protocol, data order, per-rank keys), or None.  A
    standalone reader: the elastic coordinator re-derives membership from
    disk after ``coordinator_loss`` without constructing a manager."""
    path = os.path.join(os.path.abspath(directory), "epoch_meta.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def read_mid_epoch_meta(directory: str) -> Optional[dict]:
    """The mid-epoch (emergency) checkpoint's metadata sidecar, or None."""
    path = os.path.join(os.path.abspath(directory), "mid_epoch_meta.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


class CheckpointManager:
    """Thin orbax CheckpointManager wrapper keyed on completed epochs.

    ``config`` (a small JSON-able dict: model/strategy/seed/...) is written
    alongside the checkpoints and VALIDATED on construction when the
    directory already holds one — restoring foreign state (different model,
    seed, precision) either deep-fails inside orbax with an opaque shape
    error or, worse, silently resumes from the wrong run; this turns both
    into an immediate, explicit error.

    ``elastic=True`` relaxes exactly the two keys a world-resize resume
    legitimately changes (``world``, ``global_batch``) from the equality
    check — every other mismatch still fails.  The on-disk config is NOT
    rewritten: it keeps recording the run's ORIGINAL topology, and the
    elastic metadata sidecars carry the per-save truth."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 config: Optional[dict] = None, *, elastic: bool = False):
        directory = os.path.abspath(directory)
        self._dir = directory
        self._mid = None  # lazy orbax manager for mid-epoch checkpoints
        self._elastic = elastic
        self._config_path = os.path.join(directory, "trainer_config.json")
        if config is not None:
            config = {**config,
                      "state_format_version": STATE_FORMAT_VERSION}
        if config is not None and os.path.exists(self._config_path):
            with open(self._config_path) as f:
                try:
                    existing = json.load(f)
                except json.JSONDecodeError as e:
                    raise ValueError(
                        f"checkpoint dir {directory} holds a corrupt "
                        f"trainer_config.json ({e}); refusing to resume from "
                        f"an unidentifiable run — delete the directory to "
                        f"start fresh") from e
            saved_ver = existing.get("state_format_version")
            needs_stamp = saved_ver is None
            if needs_stamp:
                # Dirs written before the stamp existed: the step-counter
                # removal (version 1 -> 2) predates the stamp's introduction
                # by three rounds, so every unstamped dir on disk is KNOWN to
                # hold the version-2 structure — read it as exactly that
                # (NOT blindly as the current version) and let the
                # structural migration below decide.
                saved_ver = _UNSTAMPED_DIR_VERSION
                existing["state_format_version"] = _UNSTAMPED_DIR_VERSION
            if saved_ver == _UNSTAMPED_DIR_VERSION != STATE_FORMAT_VERSION \
                    and _v2_structure_is_current(config):
                # One-time 2 -> 3 migration: the bump only changed the
                # stored structure for stateful (compressed) strategies,
                # so a stateless run's v2 dir is accepted — and re-stamped
                # as current — rather than stranded (ADVICE r4).
                saved_ver = STATE_FORMAT_VERSION
                existing["state_format_version"] = STATE_FORMAT_VERSION
            if saved_ver != STATE_FORMAT_VERSION:
                raise ValueError(
                    f"checkpoint dir {directory} holds state-format version "
                    f"{saved_ver}, but this build writes version "
                    f"{STATE_FORMAT_VERSION}; checkpoints do not survive "
                    f"TrainState structure changes — delete the directory "
                    f"to start fresh")
            if self._config_view(existing) != self._config_view(config):
                raise ValueError(
                    f"checkpoint dir {directory} belongs to a different "
                    f"training config: saved={existing}, current={config}")
            if needs_stamp and jax.process_index() == 0:
                # Persist the one-time migration stamp only AFTER both
                # validations pass: a rejected resume attempt must never
                # modify another run's on-disk metadata.
                tmp = f"{self._config_path}.{os.getpid()}.stamp.tmp"
                with open(tmp, "w") as f:
                    json.dump(existing, f)
                os.replace(tmp, self._config_path)
        self._mngr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                                 create=True),
        )
        if config is not None and not os.path.exists(self._config_path) \
                and jax.process_index() == 0:
            # Publish the config ATOMICALLY AND EXCLUSIVELY from process 0:
            # write a complete unique temp file (crash mid-write can never
            # leave a torn trainer_config.json), then hard-link it into
            # place — link fails with FileExistsError if another run won
            # the race, in which case the loser VALIDATES against the
            # winner instead of silently overwriting it (two different
            # configs racing one empty dir must not end with one of them
            # misidentified).
            tmp = f"{self._config_path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                json.dump(config, f)
            try:
                os.link(tmp, self._config_path)
            except FileExistsError:
                with open(self._config_path) as f:
                    existing = json.load(f)
                if self._config_view(existing) != self._config_view(config):
                    raise ValueError(
                        f"checkpoint dir {directory} was concurrently "
                        f"claimed by a different training config: "
                        f"saved={existing}, current={config}")
            except OSError:
                # Filesystem without hard links: fall back to an atomic
                # (but last-writer-wins) rename.
                os.replace(tmp, self._config_path)
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)

    def _config_view(self, cfg: dict) -> dict:
        """The config as compared: under elastic mode the world-resize
        keys are excluded from equality (both sides symmetrically)."""
        if not self._elastic:
            return cfg
        return {k: v for k, v in cfg.items() if k not in _ELASTIC_FREE_KEYS}

    def latest_epoch(self) -> Optional[int]:
        """Last COMPLETED epoch saved, or None if no checkpoint exists."""
        return self._mngr.latest_step()

    def _epoch_meta_path(self) -> str:
        return os.path.join(self._dir, "epoch_meta.json")

    def save(self, epoch: int, state: TrainState,
             meta: Optional[dict] = None) -> None:
        """Persist state after ``epoch`` completed; blocks until durable.

        ``meta`` (elastic): topology/data-order sidecar for the LATEST
        epoch save — world, global_batch, protocol, per-rank data-order
        keys — written atomically after the checkpoint is durable so the
        sidecar can never describe a save that doesn't exist."""
        self._mngr.save(epoch, args=ocp.args.StandardSave(state))
        self._mngr.wait_until_finished()
        if meta is not None:
            _atomic_write_json(self._epoch_meta_path(),
                               {**meta, "epoch": epoch})

    def epoch_meta(self) -> Optional[dict]:
        return read_epoch_meta(self._dir)

    def mid_epoch_meta(self) -> Optional[dict]:
        return read_mid_epoch_meta(self._dir)

    def restore(self, state_like: TrainState,
                epoch: Optional[int] = None) -> Tuple[TrainState, int]:
        """(state, next_epoch_to_run); ``state_like`` supplies the pytree
        structure plus shardings (restored arrays land on the same mesh)."""
        if epoch is None:
            epoch = self.latest_epoch()
        if epoch is None:
            raise FileNotFoundError("no checkpoint to restore")
        abstract = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                           sharding=a.sharding),
            state_like)
        restored = self._mngr.restore(
            epoch, args=ocp.args.StandardRestore(abstract))
        return TrainState(*restored), epoch + 1

    # ------------------------------------------------------------------
    # Mid-epoch (emergency) checkpoints — the preemption path (ft/).
    #
    # A separate orbax manager under <dir>/mid_epoch keyed by the encoded
    # (epoch, step) holds AT MOST ONE checkpoint: the state after ``step``
    # batches of ``epoch``.  The data-order state needed to resume is fully
    # derivable from (seed, epoch, step) — the sampler is a fixed
    # permutation of (seed, epoch) and every PRNG fold uses the ABSOLUTE
    # batch index — so the sidecar meta records those plus the sampler
    # config for auditability, and restore needs only the step key.
    # ------------------------------------------------------------------

    def _mid_dir(self) -> str:
        return os.path.join(self._dir, "mid_epoch")

    def _mid_meta_path(self) -> str:
        return os.path.join(self._dir, "mid_epoch_meta.json")

    def _mid_mngr(self):
        if self._mid is None:
            self._mid = ocp.CheckpointManager(
                self._mid_dir(),
                options=ocp.CheckpointManagerOptions(max_to_keep=1,
                                                     create=True))
        return self._mid

    def save_mid_epoch(self, epoch: int, step: int, state: TrainState,
                       data_order: Optional[dict] = None) -> None:
        """Emergency step-level checkpoint: state after ``step`` batches of
        ``epoch``; blocks until durable (the caller is about to exit)."""
        if step >= _MID_KEY_BASE:
            raise ValueError(f"step {step} exceeds mid-epoch key space")
        m = self._mid_mngr()
        m.save(epoch * _MID_KEY_BASE + step,
               args=ocp.args.StandardSave(state))
        m.wait_until_finished()
        meta = {"epoch": epoch, "step": step}
        if data_order:
            meta["data_order"] = data_order
        _atomic_write_json(self._mid_meta_path(), meta)

    def latest_mid_epoch(self) -> Optional[Tuple[int, int]]:
        """(epoch, step) of the emergency checkpoint, or None.  The orbax
        step listing is the source of truth (the meta sidecar can lag by a
        crash between save and meta write)."""
        if not os.path.isdir(self._mid_dir()):
            return None
        key = self._mid_mngr().latest_step()
        if key is None:
            return None
        return divmod(key, _MID_KEY_BASE)

    def restore_mid_epoch(
            self, state_like: TrainState) -> Tuple[TrainState, int, int]:
        """(state, epoch, step): resume ``epoch`` from batch ``step``."""
        at = self.latest_mid_epoch()
        if at is None:
            raise FileNotFoundError("no mid-epoch checkpoint to restore")
        epoch, step = at
        abstract = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                           sharding=a.sharding),
            state_like)
        restored = self._mid_mngr().restore(
            epoch * _MID_KEY_BASE + step,
            args=ocp.args.StandardRestore(abstract))
        return TrainState(*restored), epoch, step

    def clear_mid_epoch(self) -> None:
        """Drop the emergency checkpoint (stale once its epoch completes)."""
        if os.path.exists(self._mid_meta_path()):
            os.unlink(self._mid_meta_path())
        if not os.path.isdir(self._mid_dir()):
            return
        m = self._mid_mngr()
        for key in list(m.all_steps()):
            try:
                m.delete(key)
            except (NotImplementedError, OSError):  # pragma: no cover
                break

    def close(self) -> None:
        if self._mid is not None:
            self._mid.close()
        self._mngr.close()
