"""Training: compiled SPMD steps + reference-parity epoch driver."""

from . import loop, step                                   # noqa: F401
from .loop import Trainer                                  # noqa: F401
from .step import TrainState, init_train_state, make_eval_step, \
    make_train_step                                        # noqa: F401
