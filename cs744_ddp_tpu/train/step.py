"""Compiled SPMD train / eval steps.

The reference's per-batch loop body (zero_grad -> forward -> CE loss ->
backward -> [grad sync] -> SGD step; ``/root/reference/src/Part 2a/main.py:
86-96``) becomes ONE jitted ``shard_map`` program over the data-parallel mesh:
the batch arrives sharded on the "data" axis, the gradient-sync strategy is a
collective pattern between ``jax.grad`` and the optimizer update, and
parameters/optimizer state stay replicated.  Augmentation (pad-crop/flip) and
normalization run on device inside the same program, so the host only moves
uint8 bytes.

BatchNorm: training normalizes with the *local shard's* batch statistics —
exactly the reference's per-replica BN semantics (SURVEY.md §7).  Running
stats are pmean'd across shards before being stored so the replicated state
invariant holds; this only affects evaluation and is documented in
BASELINE.md.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:                      # jax < 0.6 ships it as experimental
    from jax.experimental.shard_map import shard_map

from ..data import augment as aug
from ..ft import guard as ftguard
from ..ops import sgd
from ..ops.loss import cross_entropy
from .. import parallel
from ..parallel.mesh import DATA_AXIS

# jax 0.4.x's experimental shard_map predates the VMA type system: there are
# no replication rules for optimization_barrier (the strategies' sequencing
# primitive), so the rep checker must be off; semantics are unchanged — every
# replicated output below is produced by an explicit psum/pmean.
import inspect as _inspect

_SHARD_MAP_KW = ({"check_rep": False}
                 if "check_rep" in _inspect.signature(shard_map).parameters
                 else {})


def pvary(x: jax.Array) -> jax.Array:
    """Mark a replicated value device-varying (``lax.pcast`` where it
    exists).  On jax 0.4.x shard_map there is no VMA typing and the
    cotangent of a replicated input is already shard-local (verified: no
    auto-psum on the transpose), so the identity is semantically exact."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, DATA_AXIS, to="varying")
    return x


def maybe_cast(x: jax.Array, compute_dtype) -> jax.Array:
    """Cast activations to the compute dtype (None = keep f32)."""
    return x.astype(compute_dtype) if compute_dtype else x


def _prepare(augment, key, images):
    """The train input transform, by mode:

    ``True``   — device-side pad-crop/flip/normalize (the default: uint8 in,
                 the whole transform fused into the step's XLA program);
    ``False``  — device-side normalize only (uint8 in, augmentation off);
    ``"host"`` — images arrive PREPROCESSED (f32, already augmented and
                 normalized by the C++ host pipeline, data/native.py — the
                 reference's DataLoader-worker model); pass through.
    """
    if augment == "host":
        return images
    return aug.augment(key, images) if augment else aug.normalize(images)


def fold_and_prepare(augment, compute_dtype, key, images, *, idx=None,
                     fold_axis=True):
    """The ONE definition of the train input path's PRNG fold order and
    transform: fold the batch index first (when the caller passes one —
    the per-step path folds it on the host instead), the mesh position
    second, then prepare + cast.  Shared by the fused step, the train
    window and the forward-only window so the streams cannot drift apart
    (the phase split's validity depends on the forward window consuming
    bit-identical inputs to the train window)."""
    if idx is not None:
        key = jax.random.fold_in(key, idx)
    if fold_axis:
        key = jax.random.fold_in(key, lax.axis_index(DATA_AXIS))
    return maybe_cast(_prepare(augment, key, images), compute_dtype)


class TrainState(NamedTuple):
    params: Any
    bn_state: Any
    opt_state: sgd.SGDState


def init_train_state(init_fn, key: jax.Array, strategy=None,
                     world: int = 1) -> TrainState:
    """Seed-identical init on every process — the reference relies on
    identical seeds instead of a parameter broadcast (SURVEY.md C12); in SPMD
    the replicated init is constructed once and placed on all devices, making
    that invariant structural rather than probabilistic.

    A STATEFUL ``strategy`` (the compressed gradient-sync tiers,
    parallel/strategies.py) contributes its communication state — error
    feedback residuals, PowerSGD Q factors — to ``SGDState.comm``, stacked
    per worker for a ``world``-position mesh; stateless strategies leave
    ``comm`` None and the pytree identical to the pre-compression layout."""
    params, bn_state = init_fn(key)
    opt = sgd.init(params)
    if strategy is not None and getattr(strategy, "stateful", False):
        opt = opt._replace(comm=strategy.init_comm(params, world))
    return TrainState(params=params, bn_state=bn_state, opt_state=opt)


def apply_strategy(strategy, grads, axis_name, comm):
    """Run the gradient-sync strategy, threading communication state.

    Stateful strategies are ``(grads, axis, comm) -> (grads, comm')``;
    stateless ones are ``(grads, axis) -> grads`` and pass ``comm``
    through untouched.  The ONE dispatch point, so every execution path
    (fused step, train window, host window) threads identically."""
    if getattr(strategy, "stateful", False):
        return strategy(grads, axis_name, comm)
    return strategy(grads, axis_name), comm


def _opt_specs(strategy):
    """shard_map partition specs for the optimizer state: everything
    replicated except a stateful strategy's comm state, which is per-worker
    — stacked on a leading mesh axis and sharded over DATA_AXIS so each
    position carries only its own residual/factor slice (the global array
    a checkpoint sees is the (world, ...) stack)."""
    if not getattr(strategy, "stateful", False):
        return P()
    return sgd.SGDState(momentum=P(), comm=P(DATA_AXIS))


def _guarded_update(params, bn_state, opt_state, grads, cfg, loss, new_bn,
                    staged_opt=None):
    """The non-finite-guarded tail of a train step: one finiteness scalar
    decides, branch-free, between the SGD update and keeping the ENTIRE
    prior state (params, BN stats, momentum) — see ft/guard.py.

    ``staged_opt`` (compressed strategies) is the optimizer state with the
    strategy's freshly-written comm state: the update branch applies it,
    while the keep branch restores ``opt_state`` — the PRE-sync comm —
    so a non-finite step leaves no poisoned residuals behind."""
    ok = ftguard.finite_ok(loss, grads)
    upd_params, upd_opt = sgd.update(
        params, grads, opt_state if staged_opt is None else staged_opt, cfg)
    return (ftguard.select_update(ok, upd_params, params),
            ftguard.select_update(ok, new_bn, bn_state),
            ftguard.select_update(ok, upd_opt, opt_state), ok)


def make_train_step(apply_fn: Callable, strategy: parallel.strategies.Strategy,
                    mesh: Mesh, cfg: sgd.SGDConfig = sgd.SGDConfig(),
                    *, augment: bool = True, compute_dtype=None,
                    nonfinite_guard: bool = False,
                    inject_nonfinite: bool = False) -> Callable:
    """Build the jitted train step.

    step(state, key, images[B,32,32,3], labels[B]) -> (state, loss)
    with B = global batch, sharded over the mesh's "data" axis; images are
    uint8 (``augment`` True/False: transform on device) or preprocessed
    float32 (``augment="host"`` — see ``_prepare``).

    ``nonfinite_guard`` compiles in the finiteness check + branch-free
    conditional update (ft/guard.py) and the step returns an extra
    replicated ``ok`` scalar: (state, loss, ok).  ``inject_nonfinite``
    (chaos only) unconditionally poisons the gradients with NaN — the
    Trainer swaps this variant in for exactly one batch.  Both default
    off, leaving the program identical to the unguarded build.

    The ``local`` strategy (reference Part 1: single process, no process
    group — ``/root/reference/src/Part 1/main.py``) compiles WITHOUT
    shard_map or any axis: a plain jitted step, the degenerate world-size-1
    case, exactly as Part 1 carries no torch.distributed code.
    """
    if strategy is parallel.strategies.local:
        if mesh.devices.size != 1:
            raise ValueError("'single' strategy requires a 1-device mesh "
                             "(reference Part 1 is world_size==1)")

        @jax.jit
        def single_step(state: TrainState, key, images, labels):
            x = fold_and_prepare(augment, compute_dtype, key, images,
                                 fold_axis=False)

            def loss_fn(p):
                logits, new_bn = apply_fn(p, state.bn_state, x, train=True)
                return cross_entropy(logits, labels), new_bn

            (loss, new_bn), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params)
            if inject_nonfinite:
                grads = ftguard.inject_nan(grads)
            if nonfinite_guard:
                p, bn, opt, ok = _guarded_update(
                    state.params, state.bn_state, state.opt_state, grads,
                    cfg, loss, new_bn)
                return TrainState(p, bn, opt), loss, ok
            new_params, new_opt = sgd.update(state.params, grads,
                                             state.opt_state, cfg)
            return TrainState(new_params, new_bn, new_opt), loss

        return single_step

    def shard_body(params, bn_state, opt_state, key, images, labels):
        # Distinct augmentation stream per shard, deterministic in (key, pos);
        # the batch index is folded on the host by the per-step caller.
        x = fold_and_prepare(augment, compute_dtype, key, images)

        def loss_fn(p):
            logits, new_bn = apply_fn(p, bn_state, x, train=True)
            return cross_entropy(logits, labels), new_bn

        # Differentiate w.r.t. a device-VARYING view of the replicated
        # params: shard_map autodiff auto-psums the cotangent of an
        # invariant input (the transpose of broadcast is reduce), which
        # would pre-reduce the grads and leave the strategy's own collective
        # double-counting by a factor of world.  pcast-to-varying keeps the
        # grads genuinely shard-local so the strategy below is the ONLY
        # gradient reduction — its collective pattern, exactly once.
        params_var = jax.tree.map(pvary, params)
        (loss, new_bn), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params_var)
        if inject_nonfinite:
            # Poison BEFORE the gradient sync — a real overflow is born on
            # a shard and spreads through the collective, and so must the
            # injected one.
            grads = ftguard.inject_nan(grads)
        grads, new_comm = apply_strategy(strategy, grads, DATA_AXIS,
                                         opt_state.comm)
        staged_opt = opt_state._replace(comm=new_comm)
        new_bn = jax.tree.map(lambda a: lax.pmean(a, DATA_AXIS), new_bn)
        loss = lax.pmean(loss, DATA_AXIS)
        if nonfinite_guard:
            return _guarded_update(params, bn_state, opt_state, grads, cfg,
                                   loss, new_bn,
                                   staged_opt=staged_opt) + (loss,)
        new_params, new_opt = sgd.update(params, grads, staged_opt, cfg)
        return new_params, new_bn, new_opt, loss

    opt_spec = _opt_specs(strategy)
    out_specs = ((P(), P(), opt_spec, P(), P()) if nonfinite_guard
                 else (P(), P(), opt_spec, P()))
    mapped = shard_map(
        shard_body, mesh=mesh,
        in_specs=(P(), P(), opt_spec, P(), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=out_specs,
        **_SHARD_MAP_KW,
    )

    if nonfinite_guard:
        @jax.jit
        def guarded_step(state: TrainState, key, images, labels):
            p, bn, opt, ok, loss = mapped(
                state.params, state.bn_state, state.opt_state, key, images,
                labels)
            return TrainState(p, bn, opt), loss, ok

        return guarded_step

    @jax.jit
    def step(state: TrainState, key, images, labels):
        new_params, new_bn, new_opt, loss = mapped(
            state.params, state.bn_state, state.opt_state, key, images, labels)
        return TrainState(new_params, new_bn, new_opt), loss

    return step


def _ring_row(buf, cnt, loss, grads, ok, idx):
    """Append one (loss, grad sqnorm, ok, step marker) row to the metric
    ring inside the scanned body (obs/ringbuf.py).  The sqnorm is computed
    on the POST-sync grads, so the write is replicated and the ring can
    carry a replicated out-spec; the loss value is the same tensor the
    non-ring path stacks into ys — observation only, bitwise-inert."""
    from ..obs import ringbuf
    gsq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
              for g in jax.tree.leaves(grads))
    return ringbuf.ring_write((buf, cnt), (loss, gsq, ok, idx))


def make_train_window(apply_fn: Callable,
                      strategy: parallel.strategies.Strategy, mesh: Mesh,
                      cfg: sgd.SGDConfig = sgd.SGDConfig(),
                      *, augment: bool = True,
                      compute_dtype=None,
                      nonfinite_guard: bool = False,
                      nonfinite_chaos_steps=(),
                      metrics_ring: bool = False) -> Callable:
    """Windowed train step: W iterations per dispatch via ``lax.scan``.

    window(state, key, epoch_images[NB,B,32,32,3], epoch_labels[NB,B],
           start, length_arr) -> (state, losses[W])

    where W = length_arr.shape[0] (static per compile), ``start`` is the
    first batch index (dynamic), and the epoch arrays stay RESIDENT on
    device across calls.  Rationale: per-call dispatch and host->device
    transfer carry fixed costs that dwarf VGG's ~6 ms of compute per batch,
    so the framework amortizes one dispatch over a full 20-iteration
    reporting window — the granularity the reference itself reports at
    (``/root/reference/src/Part 1/main.py:47-57``).  State buffers are
    donated (the optimizer update is in-place in XLA terms).

    ``nonfinite_guard`` adds the per-iteration finiteness check + select
    (ft/guard.py); the window then returns (state, losses[W], oks[W]).
    ``nonfinite_chaos_steps`` (static ints, chaos only) poisons gradients
    with NaN at those ABSOLUTE batch indices — the scan folds the absolute
    index, so one compiled program injects at exactly the planned batches
    regardless of window boundaries.  Both default off/empty: the program
    is identical to the unguarded build.

    ``metrics_ring`` swaps the per-step ys for a device-resident metric
    ring (obs/ringbuf.py) carried through the scan and DONATED alongside
    the state:

    window(state, ring, key, epoch_images, epoch_labels, start,
           length_arr) -> (state, ring)

    The scanned body writes one (loss, grad sqnorm, ok, step) row per
    iteration via dynamic-update-slice; the host drains the ring once per
    window instead of fetching stacked ys — same loss values, one fetch.
    """
    chaos_steps = tuple(int(s) for s in nonfinite_chaos_steps)

    def scan_one(apply_fn, strategy_fn, axis_ok):
        def one(carry, xs):
            if metrics_ring:
                params, bn_state, opt_state, key, buf, cnt = carry
            else:
                params, bn_state, opt_state, key = carry
            images, labels, idx = xs
            # Canonical fold order across ALL execution paths (see
            # fold_and_prepare): batch index first, mesh position second —
            # the per-step path folds the iteration on the host (loop.py)
            # and the position in make_train_step, so the windowed and
            # per-step paths consume identical augmentation streams.
            x = fold_and_prepare(augment, compute_dtype, key, images,
                                 idx=idx, fold_axis=axis_ok)

            def loss_fn(p):
                logits, new_bn = apply_fn(p, bn_state, x, train=True)
                return cross_entropy(logits, labels), new_bn

            # See make_train_step: differentiate w.r.t. a varying view so
            # the strategy is the only gradient reduction (no autodiff
            # psum of invariant-param cotangents double-counting it).
            diff_params = params if not axis_ok else jax.tree.map(
                pvary, params)
            (loss, new_bn), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(diff_params)
            if chaos_steps:
                mask = (idx == chaos_steps[0])
                for s in chaos_steps[1:]:
                    mask = mask | (idx == s)
                grads = ftguard.inject_nan(grads, mask=mask)
            grads, new_comm = strategy_fn(grads, opt_state.comm)
            staged_opt = opt_state._replace(comm=new_comm)
            if axis_ok:
                new_bn = jax.tree.map(
                    lambda a: lax.pmean(a, DATA_AXIS), new_bn)
                loss = lax.pmean(loss, DATA_AXIS)
            if nonfinite_guard:
                p, bn, opt, ok = _guarded_update(
                    params, bn_state, opt_state, grads, cfg, loss, new_bn,
                    staged_opt=staged_opt)
                if metrics_ring:
                    buf, cnt = _ring_row(buf, cnt, loss, grads, ok, idx)
                    return (p, bn, opt, key, buf, cnt), None
                return (p, bn, opt, key), (loss, ok)
            new_params, new_opt = sgd.update(params, grads, staged_opt, cfg)
            if metrics_ring:
                buf, cnt = _ring_row(buf, cnt, loss, grads,
                                     jnp.float32(1.0), idx)
                return (new_params, new_bn, new_opt, key, buf, cnt), None
            return (new_params, new_bn, new_opt, key), loss
        return one

    single = strategy is parallel.strategies.local

    def _scan(params, bn_state, opt_state, key, buf, cnt, epoch_images,
              epoch_labels, start, length_arr):
        w = length_arr.shape[0]
        imgs = lax.dynamic_slice_in_dim(epoch_images, start, w, axis=0)
        labs = lax.dynamic_slice_in_dim(epoch_labels, start, w, axis=0)
        idxs = start + jnp.arange(w, dtype=jnp.int32)
        one = scan_one(apply_fn,
                       (lambda g, c: (g, c)) if single
                       else (lambda g, c: apply_strategy(
                           strategy, g, DATA_AXIS, c)),
                       axis_ok=not single)
        carry = ((params, bn_state, opt_state, key, buf, cnt)
                 if metrics_ring else (params, bn_state, opt_state, key))
        return lax.scan(one, carry, (imgs, labs, idxs))

    if metrics_ring:
        def window_body(params, bn_state, opt_state, key, buf, cnt,
                        epoch_images, epoch_labels, start, length_arr):
            (p, bn, opt, _, buf, cnt), _ = _scan(
                params, bn_state, opt_state, key, buf, cnt, epoch_images,
                epoch_labels, start, length_arr)
            return p, bn, opt, buf, cnt
    else:
        def window_body(params, bn_state, opt_state, key, epoch_images,
                        epoch_labels, start, length_arr):
            (p, bn, opt, _), ys = _scan(
                params, bn_state, opt_state, key, None, None, epoch_images,
                epoch_labels, start, length_arr)
            if nonfinite_guard:
                losses, oks = ys
                return p, bn, opt, losses, oks
            return p, bn, opt, ys

    if single:
        if mesh.devices.size != 1:
            raise ValueError("'single' strategy requires a 1-device mesh")

        if metrics_ring:
            @partial(jax.jit, donate_argnums=(0, 1))
            def window(state: TrainState, ring, key, epoch_images,
                       epoch_labels, start, length_arr):
                out = window_body(
                    state.params, state.bn_state, state.opt_state, key,
                    ring[0], ring[1], epoch_images, epoch_labels, start,
                    length_arr)
                return TrainState(*out[:3]), (out[3], out[4])

            return window

        @partial(jax.jit, donate_argnums=(0,))
        def window(state: TrainState, key, epoch_images, epoch_labels,
                   start, length_arr):
            out = window_body(
                state.params, state.bn_state, state.opt_state, key,
                epoch_images, epoch_labels, start, length_arr)
            return (TrainState(*out[:3]),) + tuple(out[3:])

        return window

    opt_spec = _opt_specs(strategy)
    if metrics_ring:
        # The ring rows are written from replicated values (pmean'd loss,
        # post-sync grads), so the ring stays replicated like the state.
        mapped = shard_map(
            window_body, mesh=mesh,
            in_specs=(P(), P(), opt_spec, P(), P(), P(),
                      P(None, DATA_AXIS), P(None, DATA_AXIS), P(), P()),
            out_specs=(P(), P(), opt_spec, P(), P()),
            **_SHARD_MAP_KW,
        )

        @partial(jax.jit, donate_argnums=(0, 1))
        def window(state: TrainState, ring, key, epoch_images, epoch_labels,
                   start, length_arr):
            out = mapped(state.params, state.bn_state, state.opt_state, key,
                         ring[0], ring[1], epoch_images, epoch_labels,
                         start, length_arr)
            return TrainState(*out[:3]), (out[3], out[4])

        return window

    out_specs = ((P(), P(), opt_spec, P(), P()) if nonfinite_guard
                 else (P(), P(), opt_spec, P()))
    mapped = shard_map(
        window_body, mesh=mesh,
        in_specs=(P(), P(), opt_spec, P(), P(None, DATA_AXIS),
                  P(None, DATA_AXIS), P(), P()),
        out_specs=out_specs,
        **_SHARD_MAP_KW,
    )

    @partial(jax.jit, donate_argnums=(0,))
    def window(state: TrainState, key, epoch_images, epoch_labels, start,
               length_arr):
        out = mapped(state.params, state.bn_state, state.opt_state, key,
                     epoch_images, epoch_labels, start, length_arr)
        return (TrainState(*out[:3]),) + tuple(out[3:])

    return window


def make_fwd_window(apply_fn: Callable, mesh: Mesh, *, single: bool = False,
                    augment: bool = True, compute_dtype=None) -> Callable:
    """Forward-only analogue of ``make_train_window``: W augment+forward+
    loss iterations per dispatch via ``lax.scan``, same PRNG fold order and
    train=True BN semantics as the fused step, no backward/update.

    Exists for the reference's fwd/bwd phase split
    (``/root/reference/src/Part 1/main.py:33-43``) measured HONESTLY on the
    tunneled TPU backend: per-dispatch timing pays ~100 ms of host latency
    that dwarfs the 0.6 ms forward, so the split must be window-amortized
    (``Trainer.measure_phase_split``) — backward ≈ train-window − fwd-window
    per iteration, with the dispatch cost amortized to noise."""

    def fwd_body(params, bn_state, key, epoch_images, epoch_labels, start,
                 length_arr):
        w = length_arr.shape[0]
        imgs = lax.dynamic_slice_in_dim(epoch_images, start, w, axis=0)
        labs = lax.dynamic_slice_in_dim(epoch_labels, start, w, axis=0)
        idxs = start + jnp.arange(w, dtype=jnp.int32)

        def one(carry, xs):
            images, labels, idx = xs
            x = fold_and_prepare(augment, compute_dtype, key, images,
                                 idx=idx, fold_axis=not single)
            logits, _ = apply_fn(params, bn_state, x, train=True)
            loss = cross_entropy(logits, labels)
            if not single:
                loss = lax.pmean(loss, DATA_AXIS)
            return carry, loss

        _, losses = lax.scan(one, jnp.int32(0), (imgs, labs, idxs))
        return losses

    if single:
        @jax.jit
        def fwd_window(state: TrainState, key, epoch_images, epoch_labels,
                       start, length_arr):
            return fwd_body(state.params, state.bn_state, key, epoch_images,
                            epoch_labels, start, length_arr)

        return fwd_window

    mapped = shard_map(
        fwd_body, mesh=mesh,
        in_specs=(P(), P(), P(), P(None, DATA_AXIS), P(None, DATA_AXIS),
                  P(), P()),
        out_specs=P(), **_SHARD_MAP_KW)

    @jax.jit
    def fwd_window(state: TrainState, key, epoch_images, epoch_labels,
                   start, length_arr):
        return mapped(state.params, state.bn_state, key, epoch_images,
                      epoch_labels, start, length_arr)

    return fwd_window


def masked_eval_counts(logits: jax.Array, labels: jax.Array):
    """(loss_sum, correct) over valid examples; label -1 marks padding.

    Shared by the per-batch eval step and the scanned eval window so the
    masking/accounting semantics cannot drift apart."""
    valid = labels >= 0
    safe = jnp.maximum(labels, 0)
    logits = logits.astype(jnp.float32)  # full-precision loss in bf16 mode
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, safe[:, None], axis=-1)[:, 0]
    loss_sum = jnp.sum(jnp.where(valid, logz - picked, 0.0))
    correct = jnp.sum(valid & (jnp.argmax(logits, axis=-1) == safe))
    return loss_sum, correct


def make_eval_window(apply_fn: Callable, mesh: Mesh, *,
                     compute_dtype=None) -> Callable:
    """Whole-test-set evaluation in ONE dispatch: scan over [T,B,...] staged
    batches, psum counts across the mesh.  Returns (loss_sum, correct)
    over all valid (label >= 0) examples."""

    def scan_eval(params, bn_state, images, labels):
        def one(carry, xs):
            imgs, labs = xs
            x = maybe_cast(aug.normalize(imgs), compute_dtype)
            logits, _ = apply_fn(params, bn_state, x, train=False)
            loss_sum, correct = masked_eval_counts(logits, labs)
            l, c = carry
            return (l + loss_sum, c + correct), None
        # Initial carry must already be marked device-varying (each shard
        # accumulates its own partial sums) for shard_map's VMA typing.
        init = (pvary(jnp.float32(0.0)), pvary(jnp.int32(0)))
        (loss_sum, correct), _ = lax.scan(one, init, (images, labels))
        return loss_sum, correct

    def shard_body(params, bn_state, images, labels):
        loss_sum, correct = scan_eval(params, bn_state, images, labels)
        return (lax.psum(loss_sum, DATA_AXIS), lax.psum(correct, DATA_AXIS))

    mapped = shard_map(shard_body, mesh=mesh,
                       in_specs=(P(), P(), P(None, DATA_AXIS),
                                 P(None, DATA_AXIS)),
                       out_specs=(P(), P()), **_SHARD_MAP_KW)

    @jax.jit
    def evaluate(state: TrainState, images, labels):
        return mapped(state.params, state.bn_state, images, labels)

    return evaluate


def make_eval_step(apply_fn: Callable, mesh: Mesh, *,
                   compute_dtype=None) -> Callable:
    """Jitted eval step over a sharded batch.

    Returns (loss_sum, correct) summed over the GLOBAL batch via psum —
    reporting the same quantities as the reference's ``test_model``
    (``/root/reference/src/Part 1/main.py:61-76``) but computed once across
    the mesh instead of redundantly per rank.
    """

    def shard_body(params, bn_state, images, labels):
        x = maybe_cast(aug.normalize(images), compute_dtype)
        logits, _ = apply_fn(params, bn_state, x, train=False)
        # Reference accumulates per-batch mean CE; we return the per-example
        # sum so partial final batches stay exact, and divide on the host.
        # Padded examples are marked label = -1 and masked out (the final
        # test batch of 10000 % 256 = 16 examples stays exact this way).
        loss_sum, correct = masked_eval_counts(logits, labels)
        return (lax.psum(loss_sum, DATA_AXIS),
                lax.psum(correct, DATA_AXIS))

    mapped = shard_map(shard_body, mesh=mesh,
                       in_specs=(P(), P(), P(DATA_AXIS), P(DATA_AXIS)),
                       out_specs=(P(), P()), **_SHARD_MAP_KW)

    @jax.jit
    def step(state: TrainState, images, labels):
        return mapped(state.params, state.bn_state, images, labels)

    return step
