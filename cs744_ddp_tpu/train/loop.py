"""Training driver: the reference's ``run``/``train_model``/``test_model``
(``/root/reference/src/Part 2a/main.py:19-68,71-114,130-145``) rebuilt around
one compiled SPMD step.

Differences from the reference, by design (all documented in BASELINE.md):

  * one process drives all local devices; "workers" are mesh positions, and
    each mesh position sees exactly the shard the reference's
    DistributedSampler would hand that rank (data.sharding);
  * the per-batch phases (augment/forward/loss/backward/sync/step) are one
    XLA program — timing therefore reports the fused step time, fenced by
    fetching the loss values (under the tunneled TPU backend
    ``block_until_ready`` can return before computation completes); an
    optional split-phase mode additionally times a forward-only program
    for the reference's fwd/bwd split;
  * the ragged final train batch (drop_last=False) runs through a second
    compiled step at its true static shape — exact short-batch BN/CE
    semantics, same iteration count as the reference;
  * evaluation runs once across the mesh (psum'd counts) instead of
    redundantly per rank, reporting identical quantities.
"""

from __future__ import annotations

import os
import queue
import signal
import threading
import time
from typing import Callable, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import models as model_zoo
from ..data import cifar10, native, sharding
from ..ft import (FTConfig, ChaosError, NULL_CHAOS, NonFiniteError,
                  PreemptedError, PreemptionGuard, RankDeathError)
from ..ft import guard as ftguard
from ..ft import supervisor as ftsup
from ..obs import NULL, git_sha, ringbuf
from ..ops import sgd
from ..parallel import get_strategy, mesh as meshlib, strategies
from ..utils.metrics import WINDOW, WindowedTimers
from . import step as steplib

GLOBAL_BATCH = 256      # reference: batch_size=256 (Part 2a/main.py:173)
SEED = 0                # reference: torch.manual_seed(0) (main.py:80-81)


def _shard_batch_cols(n_examples: int, world: int, global_batch: int,
                      epoch: int, *, shuffle: bool, seed: int = SEED,
                      reshuffle_each_epoch: bool = False
                      ) -> Iterator[np.ndarray]:
    """Yield each global batch's device-major index columns (the sampler
    layout ``_shard_batches`` materializes).  The chunked staging producer
    consumes the RAW indices so the fused C++ gather+augment
    (native.gather_augment_u8) can write arena rows straight from the
    resident dataset, with no intermediate gathered batch."""
    per = global_batch // world
    idx = sharding.global_epoch_indices(
        n_examples, world, seed=seed, shuffle=shuffle, epoch=epoch,
        reshuffle_each_epoch=reshuffle_each_epoch)
    nfull = idx.shape[1] // per
    for b in range(nfull):
        yield idx[:, b * per:(b + 1) * per].reshape(-1)  # device-major
    if idx.shape[1] % per:
        yield idx[:, nfull * per:].reshape(-1)


def _shard_batches(split: cifar10.Split, world: int, global_batch: int,
                   epoch: int, *, shuffle: bool, seed: int = SEED,
                   reshuffle_each_epoch: bool = False
                   ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Yield [global_batch,...] host arrays laid out so that sharding dim 0
    over the mesh gives device d exactly sampler-rank d's examples.

    The final yield may be SHORT (the ragged tail): the reference's
    DataLoader uses ``drop_last=False`` (``Part 1/main.py:96-101``), so the
    short 196th/782nd batch is trained too.  The sampler's wrap-padding
    guarantees every rank holds the same per-rank count, so the tail is
    equal-sized across ranks and shards cleanly; it runs through a second
    compiled step at its own (static) shape — exact short-batch BN/CE
    semantics, no masking."""
    for cols in _shard_batch_cols(
            len(split.labels), world, global_batch, epoch, shuffle=shuffle,
            seed=seed, reshuffle_each_epoch=reshuffle_each_epoch):
        # Batch assembly via the native threaded gather (the reference's
        # DataLoader-worker equivalent); falls back to numpy fancy indexing.
        yield native.gather(split.images, cols), split.labels[cols]


def _eval_batches(split: cifar10.Split, global_batch: int
                  ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Full test set in order, final batch padded with label -1 sentinels
    (masked in the eval step) so every batch keeps the compiled shape."""
    n = len(split.labels)
    for start in range(0, n, global_batch):
        imgs = split.images[start:start + global_batch]
        labs = split.labels[start:start + global_batch]
        if len(labs) < global_batch:
            pad = global_batch - len(labs)
            imgs = np.concatenate([imgs, np.zeros((pad, 32, 32, 3), np.uint8)])
            labs = np.concatenate([labs, np.full((pad,), -1, np.int32)])
        yield imgs, labs


def emit_memory_gauges(telemetry, **attrs) -> None:
    """Host + device memory gauges at a window/epoch boundary (round 8):
    peak host RSS via ``resource.getrusage`` and live device bytes via
    ``jax.live_arrays()``.  The enabled-guard lives INSIDE so call sites
    stay one-liners; through the NULL recorder this is a single attribute
    check — no allocation, no write (pinned by the exploding-recorder
    test in tests/test_telemetry.py)."""
    if not telemetry.enabled:
        return
    import resource
    rss_kib = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    payload = {"host_rss_peak_mib": round(rss_kib / 1024.0, 1)}
    try:
        live = jax.live_arrays()
        payload["device_live_mib"] = round(
            sum(int(getattr(a, "nbytes", 0) or 0) for a in live) / 2 ** 20, 2)
        payload["device_live_arrays"] = len(live)
    except Exception:          # pragma: no cover - backend without the API
        pass
    telemetry.gauge("memory", payload, **attrs)


class Trainer:
    """Wires data + model + strategy + mesh into the reference's run()."""

    def __init__(self, model: str = "vgg11", strategy: str = "allreduce",
                 *, mesh=None, num_devices: Optional[int] = None,
                 compress_rank: Optional[int] = None,
                 global_batch: int = GLOBAL_BATCH, data_dir: str = "./data",
                 seed: int = SEED, augment: bool = True,
                 sgd_cfg: sgd.SGDConfig = sgd.SGDConfig(),
                 profile_phases: bool = False,
                 host_augment: bool = False,
                 host_chunks: int = 4,
                 precision: str = "f32",
                 reshuffle_each_epoch: bool = False,
                 limit_train_batches: Optional[int] = None,
                 limit_eval_batches: Optional[int] = None,
                 metrics_ring: Optional[int] = None,
                 log: Callable[[str], None] = print,
                 telemetry=NULL,
                 ft: Optional[FTConfig] = None,
                 elastic=None):
        self.mesh = mesh if mesh is not None else meshlib.make_mesh(num_devices)
        self.world = self.mesh.devices.size
        if global_batch % self.world:
            raise ValueError(f"global batch {global_batch} not divisible by "
                             f"world size {self.world}")
        self.global_batch = global_batch
        self.log = log
        # Structured telemetry recorder (obs/) — NULL (a stateless no-op)
        # by default, so the disabled path writes no files and allocates
        # nothing per step; the stdout print schedule above/below is the
        # reference-parity surface either way and is never redirected.
        self.telemetry = telemetry
        self.profile_phases = profile_phases
        # host_augment: the train transform runs in the C++ host pipeline
        # (data/native.py fl_augment_f32 — the reference's DataLoader-worker
        # model, Part 1/main.py:96-101) and the step receives preprocessed
        # f32 batches.  Since round 5 this dispatches scanned WINDOWS over
        # producer-staged buffers (_train_model_host_windowed — the
        # reference's own num_workers=2 + batching amortization); the
        # per-batch dispatch path remains under profile_phases.  The
        # default (False) keeps the TPU-first design: uint8 to the device,
        # transform fused into the compiled step.
        self.host_augment = host_augment
        # host_chunks: the windowed host-augment path stages each WINDOW as
        # K sub-window chunks put_global'd individually by the producer, so
        # window w+1's transfers overlap window w's device compute (round 6;
        # the round-5 path shipped ONE blocking whole-window put and left
        # the host->device link idle during compute — BASELINE.md pinned
        # that 21% short of target).  K=1 degrades exactly to round 5's
        # whole-window staging; default 4 keeps chunks ~5 batches (~3.8 MiB
        # at B=256) — deep enough to overlap, coarse enough that per-put
        # fixed costs stay amortized (bench.py chunk_sweep measures K).
        if host_chunks < 1:
            raise ValueError(f"host_chunks must be >= 1, got {host_chunks}")
        self.host_chunks = int(host_chunks)
        # Compute precision: "f32" (reference parity, the default) or "bf16"
        # (mixed precision: f32 master weights/optimizer/BN statistics/loss,
        # bf16 conv+matmul activations — the MXU's native mode).
        if precision not in ("f32", "bf16"):
            raise ValueError(f"precision must be 'f32' or 'bf16', "
                             f"got {precision!r}")
        self.precision = precision
        self.compute_dtype = compute_dtype = (
            jnp.bfloat16 if precision == "bf16" else None)
        self.augment = augment
        self.seed = seed
        # The reference never reshuffles across epochs (no sampler.set_epoch
        # call — SURVEY.md C6); opt in for proper per-epoch reshuffling.
        self.reshuffle_each_epoch = reshuffle_each_epoch
        # Optional iteration caps (None = full splits, the reference's
        # behavior): bound epoch cost for smoke runs and benchmarks.
        for name, lim in (("limit_train_batches", limit_train_batches),
                          ("limit_eval_batches", limit_eval_batches)):
            if lim is not None and lim < 1:
                raise ValueError(f"{name} must be >= 1, got {lim}")
        self.limit_train_batches = limit_train_batches
        self.limit_eval_batches = limit_eval_batches

        # Fault tolerance (ft/): all opt-in through one config.  ft=None —
        # the default — keeps every hot path byte-identical to the
        # unsupervised build: chaos is the stateless NULL_CHAOS singleton,
        # the non-finite guard is never compiled into the step programs,
        # and the staging pipeline runs exactly the PR-2 code.
        self.ft = ft
        self.chaos = ft.chaos if ft is not None else NULL_CHAOS
        self._nf_policy = ft.nonfinite if ft is not None else "off"
        if self._nf_policy not in ftguard.POLICIES:
            raise ValueError(f"nonfinite policy must be one of "
                             f"{ftguard.POLICIES}, got {self._nf_policy!r}")
        self._guard_on = self._nf_policy != "off"
        self._nf_chaos_steps = (self.chaos.steps("nonfinite_grad")
                                if self.chaos.enabled else ())
        if self._nf_chaos_steps and not self._guard_on:
            raise ValueError(
                "chaos nonfinite_grad injection requires a nonfinite policy "
                "(halt/skip/restore) — injecting NaNs with the guard off "
                "just corrupts the run")
        self._supervise = ft is not None
        self._verify_chunks = bool(ft is not None and (
            ft.verify_chunks or self.chaos.steps("corrupt_slot")))
        self.staging_degraded = bool(ft is not None and ft.degrade_staging)

        # Elastic mode (elastic/): accepts an ElasticConfig or a protocol
        # name.  "weak" pins the per-chip batch and only changes resume
        # PLANNING (the standard per-rank programs already are the weak-
        # scaling semantics); "strong" pins the global batch and swaps the
        # train window for the microshard program whose update is bitwise
        # world-invariant (elastic/step_elastic.py).
        from ..elastic.protocol import ElasticConfig, PROTOCOLS
        if isinstance(elastic, str):
            elastic = ElasticConfig(protocol=elastic)
        self.elastic = elastic
        self.rank_death = None      # (rank, epoch, step) after a death
        self.resume_plan = None     # ResumePlan from an elastic resume
        self._straggler = None      # lazily-built StragglerDetector
        if elastic is not None:
            if elastic.protocol not in PROTOCOLS:
                raise ValueError(f"elastic protocol must be one of "
                                 f"{PROTOCOLS}, got {elastic.protocol!r}")
            if elastic.protocol == "strong":
                s = elastic.microshards
                if global_batch % s:
                    raise ValueError(
                        f"elastic strong scaling: global batch "
                        f"{global_batch} not divisible by microshards {s}")
                if host_augment:
                    raise ValueError(
                        "elastic strong scaling requires device-side "
                        "augmentation (host streams are rank-shaped)")
                if profile_phases:
                    raise ValueError(
                        "elastic strong scaling is windowed-only; "
                        "profile_phases uses the per-step programs")
                if self._guard_on or self._nf_chaos_steps:
                    raise ValueError(
                        "elastic strong scaling does not support the "
                        "non-finite guard (the pinned window carries no "
                        "guarded variant)")
        # Device-resident metric ring (obs/ringbuf.py, round 8): the
        # windowed paths write per-step (loss, grad sqnorm, ok, step) rows
        # into a donated on-device ring and the host drains it ONCE per
        # window instead of fetching stacked per-step ys.  None = on by
        # default at DEFAULT_CAPACITY; 0 disables; N sets the capacity.
        # Forced off where it cannot apply: elastic strong scaling (the
        # pinned world-invariant window carries no ring variant) and
        # profile_phases (per-step dispatch is that mode's point — every
        # step already round-trips).
        if metrics_ring is None:
            ring_cap = ringbuf.DEFAULT_CAPACITY
        else:
            ring_cap = int(metrics_ring)
            if ring_cap < 0:
                raise ValueError(
                    f"metrics_ring must be >= 0, got {metrics_ring}")
            if ring_cap and ring_cap < WINDOW:
                raise ValueError(
                    f"metrics_ring capacity {ring_cap} is below the scan "
                    f"window length {WINDOW}: rows would be overwritten "
                    f"before the per-window drain")
        if profile_phases or (
                elastic is not None and elastic.protocol == "strong"):
            ring_cap = 0
        self.metrics_ring = ring_cap
        self.preempted = False
        self._preempt_guard: Optional[PreemptionGuard] = None
        self._rollback = None            # host snapshot for policy=restore
        self._chaos_step_cache: dict = {}
        self.nonfinite_skipped = 0       # run totals (epoch counts are
        self.nonfinite_restored = 0      # logged per epoch summary)
        self._epoch_nf_skipped = 0
        self._epoch_nf_restored = 0
        self.producer_failures = 0

        # Split-replacement generations: staging caches key on these, so
        # swapping a split always restages (id() reuse after GC cannot serve
        # stale device arrays).  Must exist before the property assignments.
        self._train_gen = 0
        self._test_gen = 0
        self.train_split, self.test_split, self.real_data = cifar10.load(data_dir)
        # Reference parity: these lines print len(train_loader) — the
        # per-rank BATCH count, not the example count (Part 2a/main.py:46,55).
        def ceil_div(a, b):
            return -(-a // b)

        per_rank_samples = ceil_div(len(self.train_split.labels), self.world)
        per_rank_batch = global_batch // self.world
        # The printed count is ceil (DataLoader drop_last=False parity, 782
        # at 50000/64) and matches the trained count: the ragged final batch
        # runs through its own compiled step at its true shape (_shard_batches
        # docstring), so printed == trained.
        self.log(f"Size of training set is "
                 f"{ceil_div(per_rank_samples, per_rank_batch)}")
        # The reference's test loader uses the PER-RANK batch (256/world,
        # Part 2a/main.py:50-54) over the UNsharded 10k test set, so its
        # printed size is ceil(10000/(256/world)).
        self.log(f"Size of test set is "
                 f"{ceil_div(len(self.test_split.labels), per_rank_batch)}")

        # `model` is a registry name ("vgg11", "resnet18", ...) or a custom
        # (init_fn, apply_fn) pair (used by tests to keep compiles small).
        if isinstance(model, str):
            self.model_name = model
            init_fn, self.apply_fn = model_zoo.get_model(model)
        else:
            self.model_name = "custom"
            init_fn, self.apply_fn = model
        self.strategy_name = strategy
        self.sgd_cfg = sgd_cfg
        # compress_rank only parameterizes the powersgd tier; None defers
        # to the strategy default (strategies.DEFAULT_COMPRESS_RANK).
        self.compress_rank = compress_rank
        strat = self._strategy = get_strategy(
            strategy, **({} if compress_rank is None
                         else {"compress_rank": compress_rank}))
        self.state = steplib.init_train_state(
            init_fn, jax.random.PRNGKey(seed), strat, self.world)
        # Commit the state to the mesh up front: otherwise the first
        # windowed call sees uncommitted arrays and the second call a
        # different sharding signature -> a full recompile.  Everything is
        # replicated except a stateful strategy's comm state, which lives
        # sharded over the data axis (_commit_state).
        self.state = self._commit_state(self.state)
        self.train_step = steplib.make_train_step(
            self.apply_fn, strat, self.mesh, sgd_cfg, augment=augment,
            compute_dtype=compute_dtype, nonfinite_guard=self._guard_on)
        self.train_window = steplib.make_train_window(
            self.apply_fn, strat, self.mesh, sgd_cfg, augment=augment,
            compute_dtype=compute_dtype, nonfinite_guard=self._guard_on,
            nonfinite_chaos_steps=self._nf_chaos_steps)
        if elastic is not None and elastic.protocol == "strong":
            # The pinned-math window replaces BOTH the strategy's gradient
            # reduction and the windowed program: its gather + fixed-tree
            # combine is the one float summation order every world size
            # shares (elastic/step_elastic.py) — the strategy choice still
            # names the NON-elastic programs (tail/eval/per-step).
            from ..elastic.step_elastic import make_elastic_train_window
            self.train_window = make_elastic_train_window(
                self.apply_fn, self.mesh, sgd_cfg,
                microshards=elastic.microshards, augment=augment,
                compute_dtype=compute_dtype)
        # Ring variants of the windowed programs (built alongside, compiled
        # lazily): same math, ys swapped for the donated device ring.  The
        # non-ring train_window stays built either way — bench's phase
        # split and throughput probes dispatch it directly.
        self.train_window_ring = None
        self.train_window_host_ring = None
        if self.metrics_ring:
            self.train_window_ring = steplib.make_train_window(
                self.apply_fn, strat, self.mesh, sgd_cfg, augment=augment,
                compute_dtype=compute_dtype, nonfinite_guard=self._guard_on,
                nonfinite_chaos_steps=self._nf_chaos_steps,
                metrics_ring=True)
        if host_augment:
            self.train_step_host = steplib.make_train_step(
                self.apply_fn, strat, self.mesh, sgd_cfg, augment="host",
                compute_dtype=compute_dtype, nonfinite_guard=self._guard_on)
            # The windowed host path ships COMPACT uint8 (the C++ pipeline
            # does the stochastic crop/flip; the affine normalize fuses
            # into the device step, augment=False = normalize-only): the
            # host->device link is the path's roofline (BASELINE.md), and
            # uint8 carries 4x fewer bytes than the f32 per-step format.
            self.train_window_host = steplib.make_train_window(
                self.apply_fn, strat, self.mesh, sgd_cfg, augment=False,
                compute_dtype=compute_dtype, nonfinite_guard=self._guard_on,
                nonfinite_chaos_steps=self._nf_chaos_steps)
            if self.metrics_ring:
                self.train_window_host_ring = steplib.make_train_window(
                    self.apply_fn, strat, self.mesh, sgd_cfg, augment=False,
                    compute_dtype=compute_dtype,
                    nonfinite_guard=self._guard_on,
                    nonfinite_chaos_steps=self._nf_chaos_steps,
                    metrics_ring=True)
        self.eval_window = steplib.make_eval_window(
            self.apply_fn, self.mesh, compute_dtype=compute_dtype)
        if profile_phases:
            self._fwd_only = self._make_fwd_only()

        self._batch_sharding = meshlib.batch_sharding(self.mesh)
        from jax.sharding import NamedSharding, PartitionSpec as P
        self._epoch_sharding = NamedSharding(self.mesh, P(None, meshlib.DATA_AXIS))
        if host_augment:
            # On-device window assembly for the chunked staging path: ONE
            # jitted concatenate over the K device-resident chunks (shared
            # by images and labels; retraced per distinct arity/shape).  The
            # u8 window copy it performs is ~15.7 MiB at W=20/B=256 —
            # microseconds of HBM bandwidth against the link's ~15 ms/batch
            # budget.  NEGATIVE RESULT (the rejected assembly variant):
            # dispatching the scanned window per-chunk — or scanning across
            # the chunk list — pays the tunneled backend's ~100 ms fixed
            # dispatch latency PER CHUNK (measured: tools/perf_pieces.py,
            # BASELINE.md "dispatch floor"), i.e. K x the cost round 5's
            # windowing exists to amortize; and a K-argument fused
            # scan-over-chunks program recompiles per distinct chunk-count
            # signature while still serializing the window on its LAST
            # chunk's arrival.  Concatenate-then-scan keeps one dispatch
            # per window and lets earlier chunks transfer while the
            # previous window computes.
            self._assemble_chunks = jax.jit(
                lambda *chunks: jnp.concatenate(chunks, axis=0),
                out_shardings=self._epoch_sharding)
        self._staging_arena = None          # lazily-built native.StagingArena
        self._staging_put_copies = None     # backend aliasing probe result
        self._staged_train = None   # (epoch_images, epoch_labels, tail)
        self._staged_eval = None
        self._fwd_window = None     # built lazily by measure_phase_split
        self._warmed_tail_shapes = set()
        self._warmed_window_shapes = set()
        self.last_epoch_timers: Optional[WindowedTimers] = None
        self._collective_stats_emitted = False

        if self._nf_policy == "restore":
            # "Last checkpoint" before any save is the initial state.
            self._snapshot_rollback()

        if telemetry.enabled:
            d0 = self.mesh.devices.flat[0]
            ft_manifest = None
            if ft is not None:
                ft_manifest = {
                    "nonfinite": self._nf_policy,
                    "chaos": self.chaos.spec() if self.chaos.enabled else [],
                    "put_timeout_s": ft.put_timeout_s,
                    "put_retries": ft.put_retries,
                    "stall_timeout_s": ft.stall_timeout_s,
                    "producer_restarts": ft.producer_restarts,
                    "verify_chunks": self._verify_chunks,
                    "degrade_staging": ft.degrade_staging,
                }
            telemetry.write_manifest({
                "fault_tolerance": ft_manifest,
                "model": self.model_name,
                "strategy": self.strategy_name,
                "world_size": self.world,
                "global_batch": global_batch,
                "precision": precision,
                "augment": augment,
                "host_augment": host_augment,
                "host_chunks": host_chunks,
                "elastic": (None if elastic is None else
                            {"protocol": elastic.protocol,
                             "microshards": elastic.microshards}),
                "profile_phases": profile_phases,
                "metrics_ring": self.metrics_ring,
                "seed": seed,
                "reshuffle_each_epoch": reshuffle_each_epoch,
                "real_data": self.real_data,
                "lr": sgd_cfg.lr, "momentum": sgd_cfg.momentum,
                "weight_decay": sgd_cfg.weight_decay,
                "jax_version": jax.__version__,
                "backend": jax.default_backend(),
                "device_kind": getattr(d0, "device_kind", str(d0)),
                "num_devices": self.world,
                # The native host loader degrades SILENTLY to NumPy; the
                # manifest records whether this run really had the C++
                # pipeline, and if not, why (data/native.py load_error).
                "native_loader": {"available": native.available(),
                                  "error": native.load_error()},
                "git_sha": git_sha(),
            })

    def _commit_state(self, state) -> "steplib.TrainState":
        """Commit a (host or device) TrainState to the mesh: params/BN/
        momentum replicated, a stateful strategy's comm state sharded over
        the data axis — its leaves are (world, ...) per-worker stacks and
        each mesh position owns exactly its own slice (strategies
        ``_stack_zeros_like``; the compiled programs consume it under
        ``P(DATA_AXIS)``, steplib._opt_specs).  Committing both shardings
        up front keeps every later dispatch signature-stable."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        comm = state.opt_state.comm
        stripped = state._replace(
            opt_state=state.opt_state._replace(comm=None))
        out = meshlib.put_global_tree(stripped, meshlib.replicated(self.mesh))
        if comm is not None:
            sharded = NamedSharding(self.mesh, P(meshlib.DATA_AXIS))
            comm = jax.tree.map(
                lambda a: meshlib.put_global(
                    np.asarray(jax.device_get(a)), sharded), comm)
        return out._replace(opt_state=out.opt_state._replace(comm=comm))

    # -- telemetry helpers ---------------------------------------------------

    def _emit_device_gauges(self, epoch: int) -> None:
        """Per-device ``memory_stats()`` gauges (backends without the API —
        CPU — contribute nothing)."""
        for d in self.mesh.devices.flat:
            ms = getattr(d, "memory_stats", None)
            if ms is None:
                continue
            try:
                stats = ms()
            except Exception:
                continue
            if not stats:
                continue
            keep = {k: stats[k] for k in
                    ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                     "largest_alloc_size") if k in stats} or dict(stats)
            self.telemetry.gauge("device_memory", keep, device=int(d.id),
                                 epoch=epoch)

    def _emit_collective_telemetry(self) -> None:
        """Counters/gauges for the compiled train step's collective pattern
        (analysis/stats over the pre-optimization HLO): op counts, result
        bytes and chain depth — the static cost shape of the gradient-sync
        tier, attached to the run artifact.  Best-effort: backends that
        cannot produce the HLO print contribute an error gauge instead."""
        if self._collective_stats_emitted:
            return
        self._collective_stats_emitted = True
        from ..analysis import stats as hlo_stats
        try:
            x = jax.ShapeDtypeStruct(
                (self.global_batch, 32, 32, 3),
                jnp.float32 if self.host_augment else jnp.uint8,
                sharding=self._batch_sharding)
            y = jax.ShapeDtypeStruct((self.global_batch,), jnp.int32,
                                     sharding=self._batch_sharding)
            step_fn = self.train_step_host if self.host_augment \
                else self.train_step
            txt = step_fn.lower(
                self.state, jax.random.PRNGKey(0), x, y) \
                .compiler_ir(dialect="hlo").as_hlo_text()
        except Exception as e:
            self.telemetry.gauge("collective_stats_error", repr(e))
            return
        stats = hlo_stats.collective_stats(txt)
        for op, entry in stats["ops"].items():
            self.telemetry.counter(f"collective_{op}_count", entry["count"])
            self.telemetry.counter(f"collective_{op}_result_mib",
                                   entry["result_mib"])
        self.telemetry.gauge(
            "collective_totals", {
                "total_count": stats["total_count"],
                "total_result_mib": stats["total_result_mib"],
                "chain_depth": hlo_stats.collective_chain_depth(txt)})
        # Compression headline: the uncompressed wire cost is every f32
        # gradient byte exactly once (per_param_psum's result bytes); the
        # delta against this strategy's measured collective bytes is what
        # a compressed tier buys.  gather's doubled comm clamps to 0 saved.
        grad_mib = float(sum(
            int(np.prod(a.shape, dtype=np.int64)) * 4
            for a in jax.tree.leaves(self.state.params))) / 2 ** 20
        self.telemetry.gauge(
            "comm_bytes_saved", {
                "strategy": self.strategy_name,
                "baseline_grad_mib": round(grad_mib, 3),
                "strategy_result_mib": stats["total_result_mib"],
                "saved_mib": round(
                    max(0.0, grad_mib - stats["total_result_mib"]), 3)})

    # -- metric ring (obs/ringbuf.py, round 8) ------------------------------

    def _make_ring_device(self):
        """Fresh epoch ring, committed REPLICATED to the mesh up front —
        like ``_commit_state``, so the first ring dispatch already sees the
        sharding every later (donated) dispatch returns: signature-stable
        from call one."""
        rep = meshlib.replicated(self.mesh)
        return (meshlib.put_global(
                    np.zeros((self.metrics_ring, ringbuf.N_METRICS),
                             np.float32), rep),
                meshlib.put_global(np.zeros((), np.int32), rep))

    def _ring_sds(self):
        """ShapeDtypeStructs of the ring pair, for AOT warmup lowers."""
        rep = meshlib.replicated(self.mesh)
        return (jax.ShapeDtypeStruct(
                    (self.metrics_ring, ringbuf.N_METRICS), jnp.float32,
                    sharding=rep),
                jax.ShapeDtypeStruct((), jnp.int32, sharding=rep))

    def _count_round_trip(self, site: str, **attrs) -> None:
        """Tally one device->host value fetch.  The windowed+ring epoch is
        pinned at <= windows + 2 of these (per-window drains, the ragged
        tail, the eval fetch); the per-step path honestly records one per
        iteration — the contrast the ring exists to remove."""
        if self.telemetry.enabled:
            self.telemetry.counter("host_round_trips", 1, site=site, **attrs)

    def _consume_ring(self, buf_host, writes_total: int, w: int,
                      per_iter: float, timers: WindowedTimers,
                      epoch: int) -> np.ndarray:
        """Feed one drained window into the reference-parity timers (and,
        when telemetry is on, the JSONL step stream with reconstructed
        absolute step indices + grad sqnorms).  Returns the ok column for
        the non-finite policy layer.  ``buf_host`` is the already-fetched
        buffer — the ONE round-trip happened inside the timed span."""
        rows = ringbuf.drain_rows(buf_host, writes_total, w)
        losses, gsq, oks, steps = ringbuf.split_columns(rows)
        if self.telemetry.enabled:
            for l, g, s in zip(losses, gsq, steps):
                timers.record(float(l), per_iter,
                              extra={"grad_sqnorm": float(g),
                                     "step_index": int(s)})
        else:
            for l in losses:
                timers.record(float(l), per_iter)
        return oks

    # -- fault tolerance (ft/) ----------------------------------------------

    def _snapshot_rollback(self) -> None:
        """Host copy of the current state — the ``--nonfinite=restore``
        rollback target, refreshed after every checkpoint save.  A HOST
        copy: the windowed programs donate their state buffers, so a kept
        device reference would be invalidated by the next dispatch."""
        self._rollback = jax.tree.map(
            lambda a: np.asarray(jax.device_get(a)), self.state)

    def _restore_rollback(self) -> None:
        # _commit_state restores the dual sharding layout (replicated state,
        # data-sharded comm) from the host snapshot.
        self.state = self._commit_state(self._rollback)

    def _handle_nonfinite(self, oks, epoch: int) -> bool:
        """Host-side reaction to the fetched per-step ``ok`` flags.  The
        on-device select already kept the prior state for every bad step —
        this layer only counts and applies the policy.  Returns True when
        the state was rolled back (policy=restore)."""
        oks = np.asarray(oks)
        bad = int(oks.size - np.count_nonzero(oks))
        if bad == 0:
            return False
        if self._nf_policy == "halt":
            raise NonFiniteError(
                f"non-finite loss/grad-norm in epoch {epoch} "
                f"(policy=halt; the bad update was NOT applied)")
        if self._nf_policy == "skip":
            self._epoch_nf_skipped += bad
            self.nonfinite_skipped += bad
            self.telemetry.counter("nonfinite_skipped", bad, epoch=epoch)
            return False
        # restore: the select already skipped the bad update; additionally
        # rewind to the last checkpoint snapshot — steps since it are lost
        # (training continues with the NEXT batch, not a replay).
        self._epoch_nf_restored += bad
        self.nonfinite_restored += bad
        self.telemetry.counter("nonfinite_restored", bad, epoch=epoch)
        self._restore_rollback()
        self.log(f"Non-finite step: state rolled back to the last "
                 f"checkpoint snapshot (epoch {epoch})")
        return True

    def _fetch_step(self, out):
        """Advance ``self.state`` from a per-step program result, absorbing
        the guarded arity; returns (loss, ok_or_None) as host values (the
        loss fetch is the completion fence either way)."""
        self._count_round_trip("step_fetch")
        if self._guard_on:
            self.state, loss, ok = out
            return float(loss), bool(ok)
        self.state, loss = out
        return float(loss), None

    def _chaos_nf_step(self, host: bool):
        """The per-step chaos variant: same program as train_step(_host)
        plus an unconditional NaN injection into the gradients.  Built
        lazily (one extra compile only on chaos runs) and swapped in for
        exactly the planned batch by the per-step paths — the windowed
        paths instead bake the absolute-index mask into their one program
        (make_train_window nonfinite_chaos_steps)."""
        cache_key = "host" if host else "dev"
        fn = self._chaos_step_cache.get(cache_key)
        if fn is None:
            fn = steplib.make_train_step(
                self.apply_fn, self._strategy, self.mesh,
                self.sgd_cfg, augment="host" if host else self.augment,
                compute_dtype=self.compute_dtype, nonfinite_guard=True,
                inject_nonfinite=True)
            self._chaos_step_cache[cache_key] = fn
        return fn

    def _record_chaos(self, site: str, step: int) -> None:
        self.telemetry.counter("chaos_injected", 1, site=site, step=step)
        self.log(f"chaos: injected {site} at step {step}")

    def _check_preempt(self, epoch: int, step: int) -> None:
        """Step-boundary preemption poll: fire any planned chaos SIGTERM
        once progress reaches its step, then raise ``PreemptedError`` if a
        signal has arrived (real or injected).  ``step`` is the number of
        batches already trained this epoch — exactly the resume point."""
        if self.chaos.enabled and self.chaos.fire_reached("preempt", step):
            if self._preempt_guard is None:
                raise RuntimeError(
                    "chaos preempt requires run(checkpoint_dir=...) — "
                    "without the guard installed SIGTERM would kill the "
                    "process uncheckpointed")
            self._record_chaos("preempt", step)
            os.kill(os.getpid(), signal.SIGTERM)
        g = self._preempt_guard
        if g is not None and g.requested:
            raise PreemptedError(epoch, step)

    def _rank_boundary(self, epoch: int, step: int, per_iter: float) -> None:
        """Window-boundary rank bookkeeping (elastic/ft): per-rank
        step-time gauges, straggler detection, and the rank-level chaos
        sites.  On this single-process SPMD runtime every rank's honest
        step time IS the shared window wall time (one program, lockstep);
        the gauges exist so the attribution seam is real — the
        ``slow_rank`` site injects a stall attributed to exactly one
        rank's gauge, which the detector must flag, and on a multi-process
        deployment the same gauges would carry genuinely distinct times.
        ``rank_death`` raises ``RankDeathError`` here — a step boundary,
        so ``step`` batches are exactly what the emergency checkpoint
        records.  No-op (and allocation-free) without ft/elastic."""
        if self.elastic is None and not self._supervise:
            return
        stalls = {}
        if self.chaos.enabled and self.chaos.fire_reached("slow_rank", step):
            planned = self.chaos.fired[-1][1]
            rank = self.chaos.seed_of("slow_rank", planned)
            stall_s = (self.ft.slow_rank_stall_s if self.ft is not None
                       else FTConfig().slow_rank_stall_s)
            self._record_chaos("slow_rank", step)
            time.sleep(stall_s)   # the rank really straggles: wall time too
            stalls[rank] = stall_s
        if self._straggler is None:
            from ..elastic.straggler import StragglerDetector
            self._straggler = StragglerDetector(self.world)
        for r in range(self.world):
            t = per_iter + stalls.get(r, 0.0)
            if self.telemetry.enabled:
                self.telemetry.gauge("rank_step_time_s", t, rank=r,
                                     epoch=epoch, step=step)
            self._straggler.observe(r, t)
        for r in self._straggler.check():
            self.log(f"elastic: rank {r} straggling "
                     f"(EWMA {self._straggler.ewma(r):.3f}s vs peers)")
            if self.telemetry.enabled:
                self.telemetry.counter("straggler_flagged", 1, rank=r,
                                       epoch=epoch, step=step)
        if self.chaos.enabled and \
                self.chaos.fire_reached("rank_death", step):
            planned = self.chaos.fired[-1][1]
            rank = self.chaos.seed_of("rank_death", planned)
            self._record_chaos("rank_death", step)
            raise RankDeathError(rank, epoch, step)

    # -- dataset splits (generation-tracked for staging-cache keys) ---------

    @property
    def train_split(self) -> cifar10.Split:
        return self._train_split

    @train_split.setter
    def train_split(self, split: cifar10.Split) -> None:
        self._train_split = split
        self._train_gen += 1

    @property
    def test_split(self) -> cifar10.Split:
        return self._test_split

    @test_split.setter
    def test_split(self, split: cifar10.Split) -> None:
        self._test_split = split
        self._test_gen += 1

    # -- device placement ---------------------------------------------------

    def _put(self, images: np.ndarray, labels: np.ndarray):
        return (meshlib.put_global(images, self._batch_sharding),
                meshlib.put_global(np.asarray(labels, np.int32),
                                   self._batch_sharding))

    def _make_fwd_only(self):
        from jax.sharding import PartitionSpec as P
        try:
            from jax import shard_map
        except ImportError:              # jax < 0.6: experimental namespace
            from jax.experimental.shard_map import shard_map
        from ..data import augment as aug
        from ..ops.loss import cross_entropy
        from ..parallel.mesh import DATA_AXIS
        from jax import lax

        from ..train.step import _SHARD_MAP_KW, maybe_cast

        def body(params, bn_state, images, labels):
            # host_augment feeds preprocessed f32; otherwise normalize here.
            x = images if self.host_augment else aug.normalize(images)
            x = maybe_cast(x, self.compute_dtype)
            logits, _ = self.apply_fn(params, bn_state, x, train=True)
            return lax.pmean(cross_entropy(logits, labels), DATA_AXIS)

        mapped = shard_map(body, mesh=self.mesh,
                           in_specs=(P(), P(), P(DATA_AXIS), P(DATA_AXIS)),
                           out_specs=P(), **_SHARD_MAP_KW)
        return jax.jit(mapped)

    # -- on-device staging --------------------------------------------------

    def _stage_train_epoch(self, epoch: int):
        """Stage the whole epoch's batches on device: full batches as
        [NB, B, ...] arrays plus the ragged tail batch (or None) separately.

        One host->device transfer per epoch instead of one per batch —
        transfers carry a large fixed cost, and the uint8 epoch is ~150 MB.
        With the reference's never-reshuffled sampler (C6) the staging is
        reused across epochs; the cache is keyed on the split GENERATION
        (bumped by the train_split setter) and (when reshuffling) the epoch,
        so replacing ``train_split`` or enabling reshuffle restages.
        """
        cache_key = (self._train_gen,
                     epoch if self.reshuffle_each_epoch else 0)
        if self._staged_train is not None and \
                self._staged_train[0] == cache_key:
            return self._staged_train[1]
        if self.elastic is not None and self.elastic.protocol == "strong":
            return self._stage_train_epoch_canonical(epoch, cache_key)
        imgs, labs = [], []
        tail = None
        for i, l in _shard_batches(
                self.train_split, self.world, self.global_batch, epoch,
                shuffle=True, seed=self.seed,
                reshuffle_each_epoch=self.reshuffle_each_epoch):
            if i.shape[0] < self.global_batch:   # ragged tail (always last)
                tail = (meshlib.put_global(i, self._batch_sharding),
                        meshlib.put_global(l.astype(np.int32),
                                           self._batch_sharding))
                break
            imgs.append(i)
            labs.append(l)
            if self.limit_train_batches is not None and \
                    len(imgs) >= self.limit_train_batches:
                break
        if imgs:
            full = (meshlib.put_global(np.stack(imgs), self._epoch_sharding),
                    meshlib.put_global(np.stack(labs).astype(np.int32),
                                       self._epoch_sharding))
        else:  # dataset smaller than one global batch: tail-only epoch
            full = (meshlib.put_global(
                        np.zeros((0, self.global_batch, 32, 32, 3), np.uint8),
                        self._epoch_sharding),
                    meshlib.put_global(
                        np.zeros((0, self.global_batch), np.int32),
                        self._epoch_sharding))
        staged = (full[0], full[1], tail)
        self._staged_train = (cache_key, staged)
        return staged

    def _stage_train_epoch_canonical(self, epoch: int, cache_key):
        """Elastic strong-scaling staging: batch b is canonical positions
        [b*B, (b+1)*B) IN ORDER — contiguous microshards, so sharding dim 1
        over the mesh hands rank r of world M exactly its S/M microshards
        at every M.  The epoch is wrap-padded to FULL global batches (torch
        tiling, ``canonical_epoch_order``): the pinned window has no ragged
        variant, and padding must not depend on the world size."""
        split = self.train_split
        n = len(split.labels)
        nb = -(-n // self.global_batch)              # ceil: pad, don't drop
        if self.limit_train_batches is not None:
            nb = min(nb, self.limit_train_batches)
        order = sharding.canonical_epoch_order(
            n, seed=self.seed, shuffle=True, epoch=epoch,
            reshuffle_each_epoch=self.reshuffle_each_epoch,
            pad_to=nb * self.global_batch)
        idx = order[:nb * self.global_batch]
        imgs = native.gather(split.images, idx).reshape(
            (nb, self.global_batch, 32, 32, 3))
        labs = split.labels[idx].astype(np.int32).reshape(
            (nb, self.global_batch))
        staged = (meshlib.put_global(imgs, self._epoch_sharding),
                  meshlib.put_global(labs, self._epoch_sharding),
                  None)
        self._staged_train = (cache_key, staged)
        return staged

    def _warm_train_windows(self, staged):
        """AOT-compile the 20-iteration window shapes train_model will
        dispatch (full WINDOW and the ragged window) so mid-epoch compiles
        never pollute the timers — the windowed analogue of the reference's
        first-window warmup exclusion.  Called from train_model, NOT from
        staging: the bench path stages epochs but dispatches epoch-length
        windows (whose compile lands in its own excluded warmup window), and
        would pay these compiles dead.  Idempotent per shape."""
        epoch_images, epoch_labels, _ = staged
        nbatches = epoch_images.shape[0]
        key = jax.random.PRNGKey(self.seed)
        ring_on = self.train_window_ring is not None
        for w in self._window_shape_set(nbatches):
            cache_key = (w, tuple(epoch_images.shape), ring_on)
            if cache_key in self._warmed_window_shapes:
                continue
            with self.telemetry.span("compile_warmup",
                                     program="train_window", window=w):
                if ring_on:
                    self.train_window_ring.lower(
                        self.state, self._ring_sds(), key, epoch_images,
                        epoch_labels, jnp.int32(0),
                        jnp.zeros((w,), jnp.int8)).compile()
                else:
                    self.train_window.lower(
                        self.state, key, epoch_images, epoch_labels,
                        jnp.int32(0), jnp.zeros((w,), jnp.int8)).compile()
            self._warmed_window_shapes.add(cache_key)

    def _warm_tail_step(self, tail) -> None:
        """AOT-compile the tail-shape train step (idempotent per shape) so
        the ragged batch's compile never lands inside a timed iteration.
        Deliberately NOT done at staging time: the bench path stages epochs
        but never trains the tail, and would pay a dead compile."""
        cache_key = (tail[0].shape[0], str(tail[0].dtype))
        if cache_key in self._warmed_tail_shapes:
            return
        with self.telemetry.span("compile_warmup", program="train_step_tail",
                                 batch=int(tail[0].shape[0])):
            self.train_step.lower(
                self.state, jax.random.PRNGKey(self.seed), *tail).compile()
        self._warmed_tail_shapes.add(cache_key)

    def _stage_eval(self):
        cache_key = self._test_gen
        if self._staged_eval is not None and \
                self._staged_eval[0] == cache_key:
            return self._staged_eval[1]
        imgs, labs = [], []
        for i, l in _eval_batches(self.test_split, self.global_batch):
            imgs.append(i)
            labs.append(l.astype(np.int32))
            if self.limit_eval_batches is not None and \
                    len(imgs) >= self.limit_eval_batches:
                break
        staged = (meshlib.put_global(np.stack(imgs), self._epoch_sharding),
                  meshlib.put_global(np.stack(labs), self._epoch_sharding))
        self._staged_eval = (cache_key, staged)
        return staged

    # -- reference-parity loops --------------------------------------------

    def train_model(self, epoch: int, start_step: int = 0) -> WindowedTimers:
        """One training epoch with the reference's print/timing schedule.

        Default mode runs one compiled dispatch per 20-iteration window
        (lax.scan inside), timed with value-fetch fences — the same
        granularity the reference reports at.  ``profile_phases=True``
        switches to the per-step path, which additionally times a
        forward-only program to report the reference's fwd/bwd split.

        ``start_step`` (mid-epoch resume, ft/) skips the first N batches:
        every PRNG fold uses the ABSOLUTE batch index and the sampler is a
        fixed permutation of (seed, epoch), so training [start_step..n)
        after restoring the step checkpoint is bitwise-identical to the
        uninterrupted run's tail (pinned by tests/test_ft.py).
        """
        self._epoch_nf_skipped = 0
        self._epoch_nf_restored = 0
        timers = self._train_model_impl(epoch, start_step)
        if self._guard_on and (self._epoch_nf_skipped
                               or self._epoch_nf_restored):
            self.log(f"Non-finite guard (epoch {epoch}): "
                     f"{self._epoch_nf_skipped} update(s) skipped, "
                     f"{self._epoch_nf_restored} rollback(s)")
        return timers

    def _train_model_impl(self, epoch: int, start_step: int) -> WindowedTimers:
        if self.profile_phases:
            return self._train_model_per_step(epoch, start_step)
        if self.host_augment:
            return self._train_model_host_windowed(epoch, start_step)
        if self.telemetry.enabled:
            self._emit_collective_telemetry()
        timers = WindowedTimers(self.log, telemetry=self.telemetry,
                                epoch=epoch)
        key = jax.random.fold_in(jax.random.PRNGKey(self.seed), epoch)
        staged = self._stage_train_epoch(epoch)
        self._warm_train_windows(staged)
        epoch_images, epoch_labels, tail = staged
        nbatches = epoch_images.shape[0]
        start = start_step
        use_ring = self.train_window_ring is not None
        ring = self._make_ring_device() if use_ring else None
        ring_writes = 0
        self._check_preempt(epoch, start)
        while start < nbatches:
            # Resume windows re-align to the ABSOLUTE window grid: the
            # emergency checkpoint always lands on a boundary, so the
            # resumed run re-dispatches the exact window shapes the
            # uninterrupted run would — the bitwise-resume invariant does
            # not depend on scan-length-invariance of the compiler.
            w = min(WINDOW - start % WINDOW, nbatches - start)
            t0 = time.time()
            # The span is tagged with the gradient-sync strategy so the
            # telemetry timeline attributes window wall time per tier
            # (the compressed-collective bench reads these back).
            with self.telemetry.span("train_window",
                                     strategy=self.strategy_name,
                                     start=int(start), batches=int(w)):
                if use_ring:
                    self.state, ring = self.train_window_ring(
                        self.state, ring, key, epoch_images, epoch_labels,
                        jnp.int32(start), jnp.zeros((w,), jnp.int8))
                    ring_writes += w
                    # The window's ONE device->host round-trip: the whole
                    # ring buffer, doubling as the completion fence.
                    buf_host = np.asarray(ring[0])
                else:
                    out = self.train_window(
                        self.state, key, epoch_images, epoch_labels,
                        jnp.int32(start), jnp.zeros((w,), jnp.int8))
                    if self._guard_on:
                        self.state, losses, oks = out
                    else:
                        (self.state, losses), oks = out, None
                    losses = np.asarray(losses)  # value fetch = fence
            per_iter = (time.time() - t0) / w
            self._count_round_trip("window_drain" if use_ring
                                   else "window_fetch", epoch=epoch)
            if use_ring:
                oks = self._consume_ring(buf_host, ring_writes, w, per_iter,
                                         timers, epoch)
                if not self._guard_on:
                    oks = None
            else:
                for loss in losses:
                    timers.record(float(loss), per_iter)
            if self._nf_chaos_steps and \
                    self.chaos.fire_range("nonfinite_grad", start, start + w):
                self._record_chaos("nonfinite_grad", next(
                    s for s in self._nf_chaos_steps if start <= s < start + w))
            start += w
            if oks is not None:
                self._handle_nonfinite(oks, epoch)
            self._rank_boundary(epoch, start, per_iter)
            emit_memory_gauges(self.telemetry, epoch=epoch, step=int(start))
            self._check_preempt(epoch, start)
        if tail is not None and start_step <= nbatches:
            # The ragged final batch (drop_last=False parity) through its
            # own compiled step; host-side fold of the batch index keeps the
            # canonical (index, position) key order of both other paths.
            self._warm_tail_step(tail)  # keep the compile out of the timer
            tail_key = jax.random.fold_in(key, nbatches)
            t0 = time.time()
            loss, ok = self._fetch_step(
                self.train_step(self.state, tail_key, *tail))
            # steady=False: this lone per-dispatch sample carries the fixed
            # dispatch latency the amortized window samples do not.
            timers.record(loss, time.time() - t0, steady=False)
            if ok is not None:
                self._handle_nonfinite(np.asarray([ok]), epoch)
        self.last_epoch_timers = timers
        return timers

    def _train_model_per_step(self, epoch: int,
                              start_step: int = 0) -> WindowedTimers:
        """Per-batch dispatch path: the fwd/bwd phase split
        (``profile_phases``) and/or the host-side augmentation pipeline
        (``host_augment`` — per-batch host work is the point of that mode,
        exactly like the reference's DataLoader workers, so it is
        double-buffered the way theirs is: batch k+1 prepares on a
        producer thread while step k runs, ``_iter_host_batches``)."""
        if self.telemetry.enabled:
            self._emit_collective_telemetry()
        timers = WindowedTimers(self.log, telemetry=self.telemetry,
                                epoch=epoch)
        key = jax.random.fold_in(jax.random.PRNGKey(self.seed), epoch)
        step_fn = self.train_step_host if self.host_augment \
            else self.train_step
        self._warm_per_step_tail_shapes()
        if self.host_augment:
            batches = self._iter_host_batches(epoch, start_it=start_step)
        else:
            def device_batches():
                for it, (imgs, labs) in enumerate(_shard_batches(
                        self.train_split, self.world, self.global_batch,
                        epoch, shuffle=True, seed=self.seed,
                        reshuffle_each_epoch=self.reshuffle_each_epoch)):
                    if self.limit_train_batches is not None and \
                            it >= self.limit_train_batches:
                        break
                    if it < start_step:
                        continue
                    yield (it, *self._put(imgs, labs))
            batches = device_batches()
        self._check_preempt(epoch, start_step)
        for it, x, y in batches:
            step_key = jax.random.fold_in(key, it)
            fwd_time = None
            if self.profile_phases:
                t0 = time.time()
                # np.asarray (a real value fetch) is the fence: under the
                # tunneled TPU backend block_until_ready can return before
                # the computation finishes — that would time dispatch only.
                np.asarray(self._fwd_only(
                    self.state.params, self.state.bn_state, x, y))
                fwd_time = time.time() - t0
            fn = step_fn
            if self._nf_chaos_steps and it in self._nf_chaos_steps and \
                    self.chaos.fire("nonfinite_grad", it):
                # Swap in the NaN-injecting variant for exactly this batch.
                self._record_chaos("nonfinite_grad", it)
                fn = self._chaos_nf_step(bool(self.host_augment))
            t0 = time.time()
            loss, ok = self._fetch_step(fn(self.state, step_key, x, y))
            # The fused step contains its own forward; the separately-timed
            # forward-only program is ONLY used to report the reference's
            # fwd/bwd split (backward ≈ fused − forward) and is excluded
            # from the step time so totals aren't inflated.
            step_time = time.time() - t0
            timers.record(loss, step_time, fwd_time)
            if ok is not None:
                self._handle_nonfinite(np.asarray([ok]), epoch)
            self._check_preempt(epoch, it + 1)
        self.last_epoch_timers = timers
        return timers

    def _host_aug_params(self, n: int, epoch: int, it: int):
        """The counter-based host augmentation stream: deterministic in
        (seed, epoch, iteration) — the analogue of the device path's
        fold_in chain (a different stream, same contract), and the reason
        ALL host-augment execution paths (per-step f32, windowed uint8)
        consume bit-identical crops/flips regardless of thread or dispatch
        timing."""
        rng = np.random.default_rng([self.seed, epoch, it])
        return (rng.integers(0, 9, (n, 2), dtype=np.int32),
                rng.integers(0, 2, (n,), dtype=np.uint8))

    def _host_transform(self, imgs: np.ndarray, n: int, epoch: int,
                        it: int) -> np.ndarray:
        """C++ host-pipeline transform, f32 out (the per-step format: the
        reference DataLoader's ToTensor+Normalize product)."""
        if self.augment:
            return native.augment(imgs, *self._host_aug_params(n, epoch, it))
        return native.normalize(imgs)

    def _host_transform_u8(self, imgs: np.ndarray, n: int, epoch: int,
                           it: int) -> np.ndarray:
        """C++ host-pipeline transform, uint8 out (the windowed staging
        format: same crop/flip stream as ``_host_transform``, normalize
        deferred to the device step — 4x fewer bytes over the link)."""
        if self.augment:
            return native.augment_u8(imgs,
                                     *self._host_aug_params(n, epoch, it))
        return imgs

    def _put_host_augmented(self, imgs: np.ndarray, labs: np.ndarray,
                            epoch: int, it: int):
        """Host-transform one batch and place the resulting f32 batch.

        Runs on the prefetch producer thread; the telemetry span stack is
        thread-local, so these spans nest correctly there."""
        with self.telemetry.span("host_augment"):
            xh = self._host_transform(imgs, len(labs), epoch, it)
        with self.telemetry.span("prefetch_put"):
            return (meshlib.put_global(xh, self._batch_sharding),
                    meshlib.put_global(np.asarray(labs, np.int32),
                                       self._batch_sharding))

    # Prefetched batches queued ahead of the consumer: 2 = one in flight on
    # the producer thread plus one ready — the reference's num_workers=2
    # DataLoader keeps the same depth of completed batches ahead.
    PREFETCH_DEPTH = 2

    def _prefetch_iter(self, fill, depth: Optional[int] = None,
                       stall_timeout_s: Optional[float] = None):
        """Producer-thread prefetch scaffolding shared by both host-augment
        paths: runs ``fill(emit)`` on a daemon thread — ``emit(item)``
        enqueues and returns False once the consumer has gone away — and
        yields the emitted items in order.  ``depth`` overrides the queue
        bound (the chunked windowed path queues per-CHUNK items, so its
        bound is two windows' worth of chunks rather than two windows).
        Every producer exit path enqueues a sentinel (BaseException
        included) so the consumer can never block forever; the consumer
        polls with a timeout and drains the queue before declaring a dead
        producer sentinel-less.  ``stall_timeout_s`` (ft supervision) is
        the consumer-side hard deadline: no item within it while the
        producer looks alive raises ``StagingStalled`` — the recovery
        trigger a detection-only watchdog cannot be (it can't interrupt a
        wedged native call)."""
        q: queue.Queue = queue.Queue(maxsize=depth or self.PREFETCH_DEPTH)
        stop = threading.Event()

        def safe_put(item) -> bool:
            """Enqueue unless the consumer has gone away."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.2)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                fill(lambda item: safe_put(("item", item)))
                safe_put(("done", None))
            except BaseException as e:  # noqa: BLE001 — every exit path
                # must enqueue a sentinel or the consumer would block on an
                # empty queue forever; surfaced (and re-raised) there.
                safe_put(("err", e))

        t = threading.Thread(target=produce, daemon=True,
                             name="host-augment-prefetch")
        t.start()
        last_item_t = time.time()
        try:
            while True:
                if self.telemetry.enabled:
                    # Depth BEFORE the blocking get: 0 here means the
                    # consumer is about to stall on the producer — the
                    # pipeline-health signal this gauge exists for.
                    self.telemetry.gauge("prefetch_queue_depth", q.qsize())
                try:
                    kind, payload = q.get(timeout=1.0)
                    last_item_t = time.time()
                except queue.Empty:
                    if t.is_alive():
                        stalled = time.time() - last_item_t
                        if stall_timeout_s is not None and \
                                stalled > stall_timeout_s:
                            raise ftsup.StagingStalled(
                                f"no staged item for {stalled:.1f}s "
                                f"(deadline {stall_timeout_s}s) with the "
                                f"producer thread alive but stuck")
                        continue
                    # Producer exited; its final put may have raced our
                    # timeout, so drain non-blockingly before declaring it
                    # died without a sentinel (only then fail loudly
                    # instead of hanging).
                    try:
                        kind, payload = q.get_nowait()
                    except queue.Empty:
                        raise RuntimeError(
                            "host-augment prefetch thread exited without "
                            "delivering a batch or a completion sentinel")
                if kind == "done":
                    break
                if kind == "err":
                    raise payload
                yield payload
        finally:
            stop.set()
            t.join(timeout=10)
            if t.is_alive():
                self.log("warning: host-augment prefetch thread did not "
                         "exit within 10s")

    def _iter_host_batches(self, epoch: int, start_it: int = 0):
        """Double-buffered host-augment pipeline: yields ``(it, x, y)`` with
        batch k+1 gathered, C++-augmented and device-put on a producer
        thread while step k runs on device — the reference's
        DataLoader-worker overlap (``Part 1/main.py:96-101``), which the
        previously-serial per-step path lacked (VERDICT r3 item 6).

        The host RNG stream is counter-based in (seed, epoch, it)
        (``_host_transform``), so the prefetched stream is BIT-IDENTICAL
        to the serial one regardless of thread timing — pinned by
        tests/test_cli_and_profiling.py.  ``start_it`` (mid-epoch resume)
        skips earlier batches; the absolute ``it`` keys the stream, so the
        suffix is the uninterrupted run's suffix."""
        def fill(emit):
            for it, (imgs, labs) in enumerate(_shard_batches(
                    self.train_split, self.world, self.global_batch,
                    epoch, shuffle=True, seed=self.seed,
                    reshuffle_each_epoch=self.reshuffle_each_epoch)):
                if self.limit_train_batches is not None and \
                        it >= self.limit_train_batches:
                    break
                if it < start_it:
                    continue
                if not emit((it, *self._put_host_augmented(
                        imgs, labs, epoch, it))):
                    return

        return self._prefetch_iter(
            fill,
            stall_timeout_s=self.ft.stall_timeout_s
            if self._supervise else None)

    def _chunk_cap(self) -> int:
        """Batches per staging chunk: WINDOW split into ``host_chunks``
        equal transfers (ceil — the last chunk of a window may be ragged,
        ``_chunk_plan``)."""
        return -(-WINDOW // self.host_chunks)

    def _chunk_plan(self, w: int):
        """The chunk sizes the streaming producer emits for a ``w``-batch
        window: fixed-capacity chunks plus a ragged last.  Shared by the
        producer's flush boundaries and the assembly-program warmup (a
        skewed copy of this arithmetic would warm the wrong arity and pay
        a mid-epoch compile)."""
        cap = self._chunk_cap()
        sizes = [cap] * (w // cap)
        if w % cap:
            sizes.append(w % cap)
        return sizes

    def _probe_put_aliases_host(self, buf: np.ndarray) -> bool:
        """Does ``put_global`` of a committed numpy array on this backend
        ALIAS the host memory instead of copying it?  jax's CPU client
        zero-copies suitably-aligned numpy buffers straight into device
        arrays — under aliasing, rewriting a retired arena row would
        corrupt chunks already handed to the consumer, so the producer puts
        a private copy there instead.  The copy only costs where no real
        host->device link exists; exactly where one does (TPU/GPU), device
        memory is separate, the put must copy, and the arena stays
        zero-copy.  Probed EMPIRICALLY on an actual arena row (aliasing
        depends on backend, sharding layout and buffer alignment, not just
        the backend name)."""
        before = int(buf.flat[0])
        x = meshlib.put_global(buf, self._epoch_sharding)
        jax.block_until_ready(x)
        buf.flat[0] = np.uint8(before ^ 0xFF)
        aliased = int(np.asarray(jax.device_get(x)).flat[0]) != before
        buf.flat[0] = before
        return aliased

    def _chunk_arena(self, cap: int) -> native.StagingArena:
        """The reusable chunk-aligned staging arena (built lazily; rebuilt
        when the chunk shape changes, e.g. a test monkeypatching WINDOW).
        First build also runs the backend aliasing probe that decides
        zero-copy vs copied puts."""
        arena = self._staging_arena
        if arena is not None and arena.chunk_batches == cap:
            return arena
        # Slot budget: the prefetch queue holds up to two windows' worth of
        # transferred chunks (_iter_host_window_chunks' depth) while one
        # more fills; +2 margin so the producer only stalls on a genuinely
        # full pipe, never on arena starvation.
        chunks_per_window = len(self._chunk_plan(WINDOW))
        self._staging_arena = native.StagingArena(
            2 * chunks_per_window + 2, cap, self.global_batch)
        # Probe EVERY slot: aliasing is a per-buffer property (the CPU
        # client's 64-byte alignment criterion — StagingArena docstring),
        # and one aliased slot among non-aliased ones corrupts the stream
        # just as surely, so any aliasing at all flips the path to copies.
        self._staging_put_copies = any(
            self._probe_put_aliases_host(self._staging_arena.buffer(s))
            for s in range(self._staging_arena.nslots))
        return self._staging_arena

    def _on_put_timeout(self, elapsed_s: float) -> None:
        """Watchdog callback: a chunk device_put exceeded its deadline —
        detection-only (the put may still complete); counted so a slow link
        shows up in telemetry before it becomes a stall."""
        if self.telemetry.enabled:
            self.telemetry.counter("staging_put_timeout")
        self.log(f"ft: chunk device_put exceeded its "
                 f"{self.ft.put_timeout_s}s watchdog deadline "
                 f"({elapsed_s:.1f}s elapsed)")

    def _on_put_retry(self, attempt: int, exc: BaseException) -> None:
        if self.telemetry.enabled:
            self.telemetry.counter("staging_put_retry")
        self.log(f"ft: chunk device_put attempt {attempt + 1} failed "
                 f"({exc!r}); retrying with backoff")

    def _supervised_put(self, src, lo: int, hi: int):
        """A chunk ``put_global`` under ft supervision: chaos injection
        (``put_fail`` raises once, ``put_delay`` sleeps past the watchdog
        once — both keyed to the chunk's ABSOLUTE batch range [lo, hi)),
        a detection-only watchdog on the put itself, and bounded
        exponential-backoff retry.  Without an FTConfig this is exactly
        ``meshlib.put_global``."""
        if not self._supervise:
            return meshlib.put_global(src, self._epoch_sharding)

        def attempt():
            if self.chaos.enabled and \
                    self.chaos.fire_range("put_fail", lo, hi):
                self._record_chaos("put_fail", lo)
                raise ChaosError(
                    f"injected transient chunk device_put failure "
                    f"(batches [{lo}, {hi}))")
            delay = self.chaos.enabled and \
                self.chaos.fire_range("put_delay", lo, hi)
            with ftsup.Watchdog(self.ft.put_timeout_s,
                                on_timeout=self._on_put_timeout):
                if delay:
                    self._record_chaos("put_delay", lo)
                    time.sleep(2.0 * self.ft.put_timeout_s)
                return meshlib.put_global(src, self._epoch_sharding)

        return ftsup.call_with_retry(
            attempt, attempts=self.ft.put_retries,
            backoff_base_s=self.ft.backoff_base_s,
            on_retry=self._on_put_retry)

    def _iter_host_window_chunks(self, epoch: int, start_it: int = 0):
        """Chunked, double-buffered windowed host-augment pipeline (round
        6).  Round 5 staged each window as ONE blocking whole-window
        ``put_global``: the host->device link idled while the previous
        window computed, and BASELINE.md pinned the path 21% short of its
        target naming exactly this lever.  Here the producer thread fills
        chunk-aligned arena rows via the FUSED C++ gather+augment
        (``native.gather_augment_u8`` — straight from the resident dataset
        into the staging row, collapsing the former gather -> augment ->
        np.stack three-copy chain to one) and ``put_global``s each chunk
        individually, so window w+1's chunk transfers overlap the
        consumer's dispatch of window w; the consumer reassembles the
        device-resident chunks (``_assemble_chunks``) and dispatches the
        scanned window exactly as round 5 did.  Buffers stay UINT8
        (crop/flip host-side, normalize fused into the device step): the
        path's roofline is the host->device link, and uint8 quarters its
        traffic.

        Yields ``("chunk", (k, x[k,B,...]u8, y[k,B]i32, last))`` — ``last``
        marks a window boundary — and ``("tail", (it, x, y))`` for the
        ragged final batch (its own per-step f32 shape, exactly as round
        5).  Batches are augmented with their ABSOLUTE iteration index
        (``_host_aug_params``), so the crop/flip stream is bit-identical to
        the per-step and whole-window paths regardless of ``host_chunks``
        or thread timing — pinned by tests/test_cli_and_profiling.py.

        ``start_it`` (mid-epoch resume / producer restart) skips earlier
        batches; chunk/window boundaries use ABSOLUTE batch arithmetic so
        a restarted stream stays on the same window grid.  Under an
        FTConfig the puts run supervised (``_supervised_put``), the arena
        fence wait gets a watchdog, and ``verify_chunks`` checksums every
        staged row at fill time and re-stages any row whose bytes changed
        by flush time (the buffer-reuse corruption the ``corrupt_slot``
        chaos site injects) — repair is a re-augment keyed by the same
        absolute index, so the repaired stream is bit-identical."""
        cap = self._chunk_cap()
        arena = self._chunk_arena(cap)   # probe runs pre-thread, main thread
        nfull, _ = self._per_rank_batch_counts()
        nlim = nfull if self.limit_train_batches is None \
            else min(nfull, self.limit_train_batches)
        fence_timeout = self.ft.put_timeout_s if self._supervise else None
        stall_timeout = self.ft.stall_timeout_s if self._supervise else None

        def fill(emit):
            split = self.train_split
            chunk_x = None       # arena row block for the chunk being filled
            slot = -1
            chunk_y: list = []
            chunk_meta: list = []   # (absolute it, cols) per filled row
            chunk_sums: list = []   # fill-time crc32 per row (verify_chunks)

            def fill_row(row, cols, it) -> None:
                if self.augment:
                    native.gather_augment_u8(
                        split.images, cols,
                        *self._host_aug_params(len(cols), epoch, it),
                        out=row)
                else:
                    native.gather(split.images, cols, out=row)

            def on_fence_timeout(elapsed_s):
                if self.telemetry.enabled:
                    self.telemetry.counter("staging_fence_timeout")
                self.log(f"ft: arena slot fence exceeded its "
                         f"{fence_timeout}s watchdog deadline")

            def inject_and_verify(k: int, lo: int) -> None:
                """Chaos byte corruption + checksum verify/repair, between
                fill and put — the window where a buffer-reuse bug would
                really strike."""
                if self.chaos.enabled:
                    for s in self.chaos.steps("corrupt_slot"):
                        if lo <= s < lo + k and \
                                self.chaos.fire("corrupt_slot", s):
                            self._record_chaos("corrupt_slot", s)
                            rng = self.chaos.rng("corrupt_slot", s)
                            flat = chunk_x[s - lo].reshape(-1)
                            pos = rng.integers(0, flat.size, size=8)
                            flat[pos] ^= np.uint8(rng.integers(1, 256))
                if not self._verify_chunks:
                    return
                for j in ftsup.verify_checksums(chunk_x[:k], chunk_sums):
                    it_j, cols_j = chunk_meta[j]
                    if self.telemetry.enabled:
                        self.telemetry.counter("staging_corruption_repaired")
                    self.log(f"ft: staged batch {it_j} failed its checksum; "
                             f"re-staging from the resident dataset")
                    fill_row(chunk_x[j], cols_j, it_j)
                    if ftsup.verify_checksums([chunk_x[j]],
                                              [chunk_sums[j]]):
                        raise ftsup.StagingStalled(
                            f"staged batch {it_j} fails its checksum even "
                            f"after re-staging — arena memory is unsafe")

            def flush(last: bool) -> bool:
                nonlocal chunk_x, slot
                k = len(chunk_y)
                if k == 0:
                    return True
                lo = chunk_meta[0][0]
                inject_and_verify(k, lo)
                with self.telemetry.span("chunk_put", batches=k, last=last):
                    src = chunk_x[:k]
                    if self._staging_put_copies:
                        src = src.copy()
                    x = self._supervised_put(src, lo, lo + k)
                    y = self._supervised_put(
                        np.asarray(chunk_y, np.int32), lo, lo + k)
                if not self._staging_put_copies:
                    arena.retire(slot, x)
                chunk_x, slot = None, -1
                chunk_y.clear()
                chunk_meta.clear()
                chunk_sums.clear()
                return emit(("chunk", (k, x, y, last)))

            for it, cols in enumerate(_shard_batch_cols(
                    len(split.labels), self.world, self.global_batch,
                    epoch, shuffle=True, seed=self.seed,
                    reshuffle_each_epoch=self.reshuffle_each_epoch)):
                if self.limit_train_batches is not None and \
                        it >= self.limit_train_batches:
                    break
                if it < start_it:
                    continue
                if self.chaos.enabled and \
                        self.chaos.fire("producer_crash", it):
                    self._record_chaos("producer_crash", it)
                    raise ChaosError(
                        f"injected staging producer crash at batch {it}")
                if len(cols) < self.global_batch:   # ragged tail (last)
                    if not flush(last=True):        # defensive: nlim
                        return                      # boundary flushed it
                    emit(("tail", (it, *self._put_host_augmented(
                        native.gather(split.images, cols),
                        split.labels[cols], epoch, it))))
                    return
                if chunk_x is None:
                    slot, chunk_x = arena.acquire(
                        fence_timeout_s=fence_timeout,
                        on_timeout=on_fence_timeout)
                with self.telemetry.span("host_augment"):
                    row = chunk_x[len(chunk_y)]
                    fill_row(row, cols, it)
                chunk_y.append(split.labels[cols])
                chunk_meta.append((it, cols))
                if self._verify_chunks:
                    chunk_sums.append(ftsup.batch_checksums([row])[0])
                boundary = (it + 1) % WINDOW == 0 or (it + 1) == nlim
                if (len(chunk_y) == cap or boundary) and \
                        not flush(last=boundary):
                    return

        # Per-CHUNK queue items: bound the pipe at two windows' worth of
        # chunks — same two-windows-ahead depth round 5's PREFETCH_DEPTH=2
        # gave whole-window items.
        return self._prefetch_iter(
            fill, depth=2 * len(self._chunk_plan(WINDOW)),
            stall_timeout_s=stall_timeout)

    def _iter_host_window_chunks_sync(self, epoch: int, start_it: int = 0):
        """Degraded-mode staging: the chunked pipeline's item protocol
        (``("chunk", ...)``/``("tail", ...)``) produced SYNCHRONOUSLY on
        the consumer thread — no producer thread, no arena, one k=1 chunk
        per batch from a private buffer.  This is the graceful-degradation
        target after staging failures exhaust their restart budget: it
        loses the transfer/compute overlap but keeps the stream
        BIT-IDENTICAL — augmentation is keyed by the absolute batch index
        and window results are chunk-composition independent (the K1-vs-K2
        pin in tests/test_cli_and_profiling.py), so the windows dispatched
        downstream are exactly the ones the healthy pipeline would have
        dispatched."""
        nfull, _ = self._per_rank_batch_counts()
        nlim = nfull if self.limit_train_batches is None \
            else min(nfull, self.limit_train_batches)
        split = self.train_split
        for it, cols in enumerate(_shard_batch_cols(
                len(split.labels), self.world, self.global_batch,
                epoch, shuffle=True, seed=self.seed,
                reshuffle_each_epoch=self.reshuffle_each_epoch)):
            if self.limit_train_batches is not None and \
                    it >= self.limit_train_batches:
                break
            if it < start_it:
                continue
            if len(cols) < self.global_batch:   # ragged tail
                yield ("tail", (it, *self._put_host_augmented(
                    native.gather(split.images, cols),
                    split.labels[cols], epoch, it)))
                return
            buf = np.empty((1, self.global_batch, 32, 32, 3), np.uint8)
            with self.telemetry.span("host_augment"):
                if self.augment:
                    native.gather_augment_u8(
                        split.images, cols,
                        *self._host_aug_params(len(cols), epoch, it),
                        out=buf[0])
                else:
                    native.gather(split.images, cols, out=buf[0])
            with self.telemetry.span("chunk_put", batches=1, degraded=True):
                x = meshlib.put_global(buf, self._epoch_sharding)
                y = meshlib.put_global(
                    np.asarray([split.labels[cols]], np.int32),
                    self._epoch_sharding)
            last = (it + 1) % WINDOW == 0 or (it + 1) == nlim
            yield ("chunk", (1, x, y, last))

    def _per_rank_batch_counts(self):
        """(nfull, tail_per): full per-rank batch count and ragged per-rank
        tail size, from the sampler's ceil wrap-padding — the ONE
        derivation shared by every warmup that must predict the epoch's
        dispatch shapes (a skewed copy yields a mid-epoch compile landing
        inside a timed window)."""
        per = self.global_batch // self.world
        per_rank = -(-len(self.train_split.labels) // self.world)
        return divmod(per_rank, per)

    @staticmethod
    def _window_shape_set(nbatches: int):
        """Distinct scan-window lengths a windowed epoch of ``nbatches``
        full batches dispatches: the full WINDOW plus the ragged last
        group.  Shared by the device and host windowed warmups."""
        shapes = {min(WINDOW, nbatches)} if nbatches else set()
        if nbatches % WINDOW:
            shapes.add(nbatches % WINDOW)
        return shapes

    def _host_window_shapes(self):
        """The window sizes _iter_host_window_chunks will close with a
        ``last`` chunk, computed host-side so compiles can be warmed up
        front."""
        nfull, _ = self._per_rank_batch_counts()
        if self.limit_train_batches is not None:
            nfull = min(nfull, self.limit_train_batches)
        return self._window_shape_set(nfull)

    def _train_model_host_windowed(self, epoch: int,
                                   start_step: int = 0) -> WindowedTimers:
        """Windowed host-augment epoch: scanned dispatches over
        chunk-staged C++-augmented buffers (``_iter_host_window_chunks``),
        the reference's print/timing schedule.  The default host-augment
        mode since round 5 — the per-step path remains under
        ``profile_phases`` (where per-batch dispatch is the point).

        Under an FTConfig this is also the supervised path: a staging
        failure (producer death, injected or real; consumer stall past the
        deadline) discards the partially-assembled window and restarts the
        producer from the last TRAINED step — once — then degrades to
        synchronous per-batch staging (``_iter_host_window_chunks_sync``).
        Both recoveries preserve the training stream bitwise: re-staged
        batches are keyed by absolute index, and ``trained`` only advances
        at dispatched-window granularity, so nothing is half-applied."""
        if self.telemetry.enabled:
            self._emit_collective_telemetry()
        timers = WindowedTimers(self.log, telemetry=self.telemetry,
                                epoch=epoch)
        key = jax.random.fold_in(jax.random.PRNGKey(self.seed), epoch)
        self._warm_per_step_tail_shapes()
        # Warm the window + assembly compiles so none lands inside a timed
        # window.
        host_ring = self.train_window_host_ring is not None
        for w in self._host_window_shapes():
            cache_key = ("host", w, self.global_batch, host_ring)
            if cache_key not in self._warmed_window_shapes:
                x_sds = jax.ShapeDtypeStruct(
                    (w, self.global_batch, 32, 32, 3), jnp.uint8,
                    sharding=self._epoch_sharding)
                y_sds = jax.ShapeDtypeStruct(
                    (w, self.global_batch), jnp.int32,
                    sharding=self._epoch_sharding)
                with self.telemetry.span("compile_warmup",
                                         program="train_window_host",
                                         window=w):
                    if host_ring:
                        self.train_window_host_ring.lower(
                            self.state, self._ring_sds(), key, x_sds, y_sds,
                            jnp.int32(0), jnp.zeros((w,), jnp.int8)).compile()
                    else:
                        self.train_window_host.lower(
                            self.state, key, x_sds, y_sds, jnp.int32(0),
                            jnp.zeros((w,), jnp.int8)).compile()
                self._warmed_window_shapes.add(cache_key)
            pattern = tuple(self._chunk_plan(w))
            if len(pattern) > 1:
                akey = ("assemble", pattern, self.global_batch)
                if akey not in self._warmed_window_shapes:
                    def _sds(c, trailing, dtype):
                        return jax.ShapeDtypeStruct(
                            (c, self.global_batch) + trailing, dtype,
                            sharding=self._epoch_sharding)
                    with self.telemetry.span("compile_warmup",
                                             program="assemble_chunks",
                                             chunks=len(pattern)):
                        self._assemble_chunks.lower(
                            *[_sds(c, (32, 32, 3), jnp.uint8)
                              for c in pattern]).compile()
                        self._assemble_chunks.lower(
                            *[_sds(c, (), jnp.int32)
                              for c in pattern]).compile()
                    self._warmed_window_shapes.add(akey)
        trained = start_step            # absolute batches applied to state
        ring = self._make_ring_device() if host_ring else None
        ring_writes = 0
        restarts_left = self.ft.producer_restarts if self._supervise else 0
        self._check_preempt(epoch, trained)

        def make_iter(start):
            if self.staging_degraded:
                return self._iter_host_window_chunks_sync(epoch, start)
            return self._iter_host_window_chunks(epoch, start)

        chunk_iter = make_iter(trained)
        chunks_x, chunks_y = [], []
        while True:
            try:
                # chunk_wait: how long the consumer stalls on the producer —
                # with healthy overlap this is ~0 except at the first window.
                with self.telemetry.span("chunk_wait"):
                    item = next(chunk_iter, None)
            except Exception as e:
                # Staging failed: the producer died (ChaosError or a real
                # exception re-raised by _prefetch_iter) or the consumer's
                # stall deadline fired (StagingStalled).  Nothing trained
                # from the lost chunks — drop the partial window and
                # re-stage from ``trained``; the counter-keyed stream makes
                # the retake bit-identical.
                if not self._supervise:
                    raise
                self.producer_failures += 1
                if self.telemetry.enabled:
                    self.telemetry.counter("producer_failure",
                                           error=type(e).__name__)
                try:
                    chunk_iter.close()
                except Exception:  # pragma: no cover - best-effort cleanup
                    pass
                chunks_x, chunks_y = [], []
                if restarts_left > 0:
                    restarts_left -= 1
                    if self.telemetry.enabled:
                        self.telemetry.counter("producer_restart")
                    self.log(f"ft: staging failed at step {trained} "
                             f"({type(e).__name__}: {e}); restarting the "
                             f"producer from step {trained}")
                    chunk_iter = make_iter(trained)
                    continue
                self.staging_degraded = True
                if self.telemetry.enabled:
                    self.telemetry.counter("staging_degraded")
                self.log(f"ft: staging failed again at step {trained} "
                         f"({type(e).__name__}: {e}); restart budget "
                         f"exhausted — degrading to synchronous per-batch "
                         f"staging (stream unchanged, overlap lost)")
                chunk_iter = make_iter(trained)
                continue
            if item is None:
                break
            kind, payload = item
            if kind == "tail":   # ragged tail through its own per-step shape
                it, x, y = payload
                t0 = time.time()
                out = self.train_step_host(
                    self.state, jax.random.fold_in(key, it), x, y)
                loss, ok = self._fetch_step(out)  # value fetch = fence
                # steady=False: lone per-dispatch sample carries the fixed
                # dispatch latency the amortized window samples do not.
                timers.record(loss, time.time() - t0, steady=False)
                trained = it + 1
                if ok is not None:
                    self._handle_nonfinite(np.asarray([ok]), epoch)
                self._check_preempt(epoch, trained)
                continue
            k, x, y, last = payload
            chunks_x.append(x)
            chunks_y.append(y)
            if self.telemetry.enabled:
                self.telemetry.gauge("window_chunks_pending", len(chunks_x))
            if not last:
                continue
            # Window boundary: assemble the device-resident chunks and
            # dispatch ONE scanned window, exactly as round 5 (a
            # single-chunk window skips the concatenate — the K=1
            # degenerate case IS round 5's whole-window path).
            if len(chunks_x) == 1:
                xw, yw = chunks_x[0], chunks_y[0]
            else:
                xw = self._assemble_chunks(*chunks_x)
                yw = self._assemble_chunks(*chunks_y)
            chunks_x, chunks_y = [], []
            w = int(xw.shape[0])
            t0 = time.time()
            # start=trained: dynamic_slice clamps it to 0 for these
            # exact-length window arrays (value-identical), while making
            # the scan's step indices ABSOLUTE — which is what the
            # compiled-in nonfinite-chaos masks are keyed by.
            if host_ring:
                self.state, ring = self.train_window_host_ring(
                    self.state, ring, key, xw, yw, jnp.int32(trained),
                    jnp.zeros((w,), jnp.int8))
                ring_writes += w
                buf_host = np.asarray(ring[0])  # one fetch = fence
            else:
                out = self.train_window_host(
                    self.state, key, xw, yw, jnp.int32(trained),
                    jnp.zeros((w,), jnp.int8))
                if self._guard_on:
                    self.state, losses, oks = out
                else:
                    (self.state, losses), oks = out, None
                losses = np.asarray(losses)  # value fetch = fence
            per_iter = (time.time() - t0) / w
            self._count_round_trip("window_drain" if host_ring
                                   else "window_fetch", epoch=epoch)
            if host_ring:
                oks = self._consume_ring(buf_host, ring_writes, w, per_iter,
                                         timers, epoch)
                if not self._guard_on:
                    oks = None
            else:
                for loss in losses:
                    timers.record(float(loss), per_iter)
            if self._nf_chaos_steps and self.chaos.fire_range(
                    "nonfinite_grad", trained, trained + w):
                self._record_chaos("nonfinite_grad", next(
                    s for s in self._nf_chaos_steps
                    if trained <= s < trained + w))
            trained += w
            if oks is not None:
                self._handle_nonfinite(oks, epoch)
            self._rank_boundary(epoch, trained, per_iter)
            emit_memory_gauges(self.telemetry, epoch=epoch, step=int(trained))
            self._check_preempt(epoch, trained)
        self.last_epoch_timers = timers
        return timers

    def _warm_per_step_tail_shapes(self) -> None:
        """AOT-compile the ragged-tail shapes of the per-step programs.

        The full-batch compile lands in the first (warmup) window, which the
        reference's protocol excludes — but the tail arrives at the LAST
        iteration, squarely inside steady state, where a fresh multi-second
        compile would corrupt steady_step_times and the epoch total.  Warm
        both per-step programs at the tail shape up front instead."""
        nfull, tail_per = self._per_rank_batch_counts()
        will_train_tail = tail_per and (self.limit_train_batches is None
                                        or self.limit_train_batches > nfull)
        if not will_train_tail:
            return
        tb = tail_per * self.world
        dtype = np.float32 if self.host_augment else np.uint8
        dtype_name = np.dtype(dtype).name
        x = jax.ShapeDtypeStruct((tb, 32, 32, 3), dtype,
                                 sharding=self._batch_sharding)
        y = jax.ShapeDtypeStruct((tb,), jnp.int32,
                                 sharding=self._batch_sharding)
        key = jax.random.PRNGKey(self.seed)
        step_fn = self.train_step_host if self.host_augment \
            else self.train_step
        if (tb, dtype_name) not in self._warmed_tail_shapes:
            with self.telemetry.span("compile_warmup",
                                     program="per_step_tail", batch=tb):
                step_fn.lower(self.state, key, x, y).compile()
            self._warmed_tail_shapes.add((tb, dtype_name))
        if self.profile_phases and \
                ("fwd", tb, dtype_name) not in self._warmed_tail_shapes:
            with self.telemetry.span("compile_warmup",
                                     program="fwd_only_tail", batch=tb):
                self._fwd_only.lower(
                    self.state.params, self.state.bn_state, x, y).compile()
            self._warmed_tail_shapes.add(("fwd", tb, dtype_name))

    def test_model(self) -> Tuple[float, int, float]:
        """Full-test-set evaluation in one dispatch; prints the reference's
        line (``Part 1/main.py:74-76``): per-batch-averaged CE, correct/total,
        %."""
        with self.telemetry.span("eval"):
            images, labels = self._stage_eval()
            loss_sum, corr = self.eval_window(self.state, images, labels)
            # Value fetches inside the span so it covers real device work.
            loss_sum, corr = float(loss_sum), int(corr)
            self._count_round_trip("eval")
        n = len(self.test_split.labels)
        if self.limit_eval_batches is not None:
            n = min(n, self.limit_eval_batches * self.global_batch)
        # Reference divides the accumulated per-batch mean losses by the
        # number of batches; we accumulate per-example sums, so divide by n
        # (equal when batches are full; exact even on the ragged tail).
        avg_loss = float(loss_sum) / n
        correct = int(corr)
        acc = 100.0 * correct / n
        self.log("Test set: Average loss: {:.4f}, Accuracy: {}/{} ({:.0f}%)\n"
                 .format(avg_loss, correct, n, acc))
        return avg_loss, correct, acc

    def _elastic_meta(self, epoch: int) -> dict:
        """Topology + data-order metadata written into every checkpoint
        sidecar (round 6): enough for ``elastic.protocol.plan_resume`` to
        map saved progress onto a DIFFERENT world size, plus per-rank
        data-order keys so a dataset/seed drift under the checkpoint fails
        loudly at resume time instead of silently desynchronizing the
        example stream.  Written for every run, elastic or not — that is
        the forward-compat half of the story (old checkpoints without it
        restore as world=1 via ``elastic.protocol.world_of``)."""
        from ..elastic.protocol import rank_data_keys
        meta = {
            "world": self.world,
            "global_batch": self.global_batch,
            "seed": self.seed,
            "reshuffle_each_epoch": self.reshuffle_each_epoch,
            "rank_keys": list(rank_data_keys(
                len(self.train_split.labels), self.world, seed=self.seed,
                epoch=epoch,
                reshuffle_each_epoch=self.reshuffle_each_epoch)),
        }
        if self.elastic is not None:
            meta["protocol"] = self.elastic.protocol
            if self.elastic.protocol == "strong":
                meta["microshards"] = self.elastic.microshards
        return meta

    def _data_order_meta(self, epoch: int, step: int) -> dict:
        """The mid-epoch sidecar's ``data_order`` payload: the historical
        resume keys plus the round-6 topology metadata."""
        return {
            "seed": self.seed, "epoch": epoch, "step": step,
            "reshuffle_each_epoch": self.reshuffle_each_epoch,
            **self._elastic_meta(epoch),
        }

    def _plan_elastic_resume(self, meta: Optional[dict],
                             start_step: int) -> int:
        """Map a mid-epoch checkpoint's progress onto THIS trainer's world
        size.  Strong scaling carries the step counter over unchanged
        (batch b covers the same canonical positions at every world); weak
        scaling re-derives it from example progress.  Validates the saved
        per-rank data-order keys against this dataset/seed first."""
        from ..elastic.protocol import (flat_meta, plan_resume,
                                        validate_rank_keys)
        flat = flat_meta(meta)
        if not flat:
            return start_step
        validate_rank_keys(flat, len(self.train_split.labels))
        plan = plan_resume(
            flat, self.world, protocol=self.elastic.protocol,
            microshards=(self.elastic.microshards
                         if self.elastic.protocol == "strong" else None),
            default_global_batch=self.global_batch)
        self.resume_plan = plan
        if plan.old_world != plan.new_world:
            self.log(
                f"elastic: resuming world {plan.old_world} -> "
                f"{plan.new_world} ({plan.protocol}); start step "
                f"{start_step} -> {plan.start_step}"
                + (f", {plan.examples_replayed} example(s) replayed"
                   if plan.examples_replayed else ""))
        return plan.start_step

    def _ckpt_state_like(self, meta: Optional[dict]):
        """(state_like, saved_world) for a checkpoint restore.  When the
        save's world differs from this trainer's (elastic resume), the comm
        stack on disk is (saved_world, ...) — build the abstract tree at
        that shape, replicated (the new mesh need not divide the old
        world); ``_absorb_restored`` reshards after the restore.  Params/
        BN/momentum are world-invariant and restore directly."""
        comm = self.state.opt_state.comm
        if comm is None:
            return self.state, self.world
        from ..elastic.protocol import flat_meta
        flat = flat_meta(meta)
        saved = int(flat.get("world") or self.world)
        if saved == self.world:
            return self.state, saved
        rep = meshlib.replicated(self.mesh)
        resized = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(
                (saved,) + tuple(a.shape[1:]), a.dtype, sharding=rep),
            comm)
        return self.state._replace(
            opt_state=self.state.opt_state._replace(comm=resized)), saved

    def _absorb_restored(self, state, saved_world: int):
        """Finish a restore: on a world mismatch, map the restored
        (saved_world, ...) comm stack onto this world — sum-conserving for
        error-feedback residuals (strategies.reshard_comm) — and re-commit
        the dual sharding layout (_commit_state)."""
        if state.opt_state.comm is None or saved_world == self.world:
            return state
        comm = strategies.reshard_comm(
            jax.device_get(state.opt_state.comm), self.world)
        return self._commit_state(
            state._replace(opt_state=state.opt_state._replace(comm=comm)))

    def run(self, epochs: int = 1,
            checkpoint_dir: Optional[str] = None,
            profile_dir: Optional[str] = None,
            publish_dir: Optional[str] = None,
            publish_every: int = 1) -> None:
        """The reference's run(): epochs of train + eval with epoch timing.

        With ``checkpoint_dir`` set, resumes from the latest saved epoch (if
        any) and persists the full TrainState after every completed epoch —
        beyond-parity (the reference keeps state only in memory); resume is
        bitwise-exact, see train/checkpoint.py.

        With ``profile_dir`` set, the first trained epoch is captured as a
        ``jax.profiler`` trace (XPlane; viewable in TensorBoard/Perfetto) —
        the superset of the reference's print-based timers promised in
        SURVEY.md §5.

        Preemption (ft/): while running, SIGTERM/SIGINT request a stop at
        the next step boundary — the in-flight dispatch finishes, an
        EMERGENCY mid-epoch checkpoint (state + (epoch, step)) is written
        if a checkpoint dir is configured, and run() returns with
        ``self.preempted`` set.  A later run() against the same dir resumes
        from that exact step — every PRNG fold and the sampler are keyed by
        (seed, epoch, absolute step), so the interrupted+resumed run is
        bitwise identical to an uninterrupted one (pinned by
        tests/test_ft.py).

        With ``publish_dir`` set, the serving half of the state (params +
        BatchNorm stats) is published as a versioned, crc-checksummed
        weight bundle every ``publish_every`` completed epochs — the
        train side of the publish/ hot-swap loop: a live serving process
        watching that directory installs each version between dispatches
        without restarts or recompiles (see cs744_ddp_tpu/publish/)."""
        start_epoch = 0
        start_step = 0
        mngr = None
        if checkpoint_dir is not None:
            from .checkpoint import CheckpointManager
            # param_tree digests the full state structure (shapes+dtypes),
            # so two "custom" models or any architecture drift fail the
            # guard; real_data catches the silent synthetic-fallback case
            # (same config keys, different dataset).
            # comm is EXCLUDED from the digest: its leaves are (world, ...)
            # stacks, and an elastic resume legitimately changes world —
            # the "strategy"/"compress_rank" keys pin its identity instead.
            digest_state = self.state._replace(
                opt_state=self.state.opt_state._replace(comm=None))
            param_tree = jax.tree.map(
                lambda a: f"{a.dtype}{list(a.shape)}", digest_state)
            mngr = CheckpointManager(checkpoint_dir, config={
                "model": self.model_name, "strategy": self.strategy_name,
                "compress_rank": self.compress_rank,
                "seed": self.seed, "precision": self.precision,
                "global_batch": self.global_batch, "world": self.world,
                "augment": self.augment,
                "reshuffle_each_epoch": self.reshuffle_each_epoch,
                "lr": self.sgd_cfg.lr, "momentum": self.sgd_cfg.momentum,
                "weight_decay": self.sgd_cfg.weight_decay,
                "limit_train_batches": self.limit_train_batches,
                "real_data": self.real_data,
                "state_digest": str(param_tree)},
                elastic=self.elastic is not None)
            # Mid-epoch (emergency) checkpoints outrank the epoch series
            # exactly when they are AHEAD of it: the emergency save for
            # epoch k is newer than the epoch k-1 save it coexists with,
            # and stale (cleared, but tolerate a crash between save and
            # clear) once epoch k itself completes.
            mid = mngr.latest_mid_epoch()
            le = mngr.latest_epoch()
            if mid is not None and (le is None or mid[0] > le):
                like, saved_world = self._ckpt_state_like(
                    mngr.mid_epoch_meta())
                restored, start_epoch, start_step = \
                    mngr.restore_mid_epoch(like)
                self.state = self._absorb_restored(restored, saved_world)
                if self.elastic is not None:
                    start_step = self._plan_elastic_resume(
                        mngr.mid_epoch_meta(), start_step)
                self.log(f"Resumed from mid-epoch checkpoint: epoch "
                         f"{start_epoch}, step {start_step}")
            elif le is not None:
                like, saved_world = self._ckpt_state_like(mngr.epoch_meta())
                restored, start_epoch = mngr.restore(like)
                self.state = self._absorb_restored(restored, saved_world)
                self.log(f"Resumed from checkpoint: epoch {start_epoch}")
            if self._nf_policy == "restore" and \
                    (mid is not None or le is not None):
                self._snapshot_rollback()   # rollback point = restored state
        publisher = None
        if publish_dir is not None:
            if publish_every < 1:
                raise ValueError(f"publish_every must be >= 1, "
                                 f"got {publish_every}")
            from ..publish import WeightPublisher
            from .checkpoint import publish_fingerprint
            digest_state = self.state._replace(
                opt_state=self.state.opt_state._replace(comm=None))
            param_tree = jax.tree.map(
                lambda a: f"{a.dtype}{list(a.shape)}", digest_state)
            publisher = WeightPublisher(
                publish_dir,
                fingerprint=publish_fingerprint({
                    "model": self.model_name,
                    "strategy": self.strategy_name,
                    "seed": self.seed, "precision": self.precision,
                    "global_batch": self.global_batch,
                    "state_digest": str(param_tree)}),
                telemetry=self.telemetry, chaos=self.chaos)
        try:
            if mngr is not None or self._supervise:
                self._preempt_guard = PreemptionGuard(log=self.log).install()
            if start_epoch >= epochs:
                self.log(f"All {epochs} epoch(s) already checkpointed; "
                         f"nothing to run"
                         + (" (profile_dir ignored)" if profile_dir else ""))
            for epoch in range(start_epoch, epochs):
                t0 = time.time()
                try:
                    if profile_dir is not None and epoch == start_epoch:
                        with jax.profiler.trace(profile_dir):
                            self.train_model(epoch, start_step=start_step)
                    else:
                        self.train_model(epoch, start_step=start_step)
                except PreemptedError as e:
                    self.preempted = True
                    if self.telemetry.enabled:
                        self.telemetry.counter("preemptions",
                                               epoch=e.epoch, step=e.step)
                    if mngr is not None:
                        with self.telemetry.span("checkpoint_save_mid_epoch",
                                                 epoch=e.epoch, step=e.step):
                            mngr.save_mid_epoch(
                                e.epoch, e.step, self.state,
                                data_order=self._data_order_meta(
                                    e.epoch, e.step))
                        self.log(f"Preempted at epoch {e.epoch} step "
                                 f"{e.step}; emergency checkpoint saved")
                    else:
                        self.log(f"Preempted at epoch {e.epoch} step "
                                 f"{e.step}; no checkpoint dir — progress "
                                 f"since the last save is lost")
                    return
                except RankDeathError as e:
                    if self.telemetry.enabled:
                        self.telemetry.counter("rank_deaths", rank=e.rank,
                                               epoch=e.epoch, step=e.step)
                    if mngr is not None:
                        with self.telemetry.span("checkpoint_save_mid_epoch",
                                                 epoch=e.epoch, step=e.step):
                            mngr.save_mid_epoch(
                                e.epoch, e.step, self.state,
                                data_order=self._data_order_meta(
                                    e.epoch, e.step))
                        self.log(f"Rank {e.rank} died at epoch {e.epoch} "
                                 f"step {e.step}; emergency checkpoint "
                                 f"saved")
                    else:
                        self.log(f"Rank {e.rank} died at epoch {e.epoch} "
                                 f"step {e.step}; no checkpoint dir — "
                                 f"progress since the last save is lost")
                    self.rank_death = (e.rank, e.epoch, e.step)
                    return
                start_step = 0
                self.log(f"Training time after {epoch + 1} epoch is "
                         f"{time.time() - t0}")
                if self.telemetry.enabled:
                    self.telemetry.gauge("epoch_time_s", time.time() - t0,
                                         epoch=epoch)
                    self._emit_device_gauges(epoch)
                    emit_memory_gauges(self.telemetry, epoch=epoch)
                self.test_model()
                if mngr is not None:
                    with self.telemetry.span("checkpoint_save", epoch=epoch):
                        mngr.save(epoch, self.state,
                                  meta=self._elastic_meta(epoch))
                    mngr.clear_mid_epoch()
                    if self._nf_policy == "restore":
                        self._snapshot_rollback()   # advance rollback point
                if publisher is not None \
                        and (epoch + 1) % publish_every == 0:
                    with self.telemetry.span("publish", epoch=epoch):
                        rec = publisher.publish(self.state)
                    self.log(f"Published weights v{rec['version']} "
                             f"({rec['bytes']} B, {rec['leaves']} leaves) "
                             f"to {publish_dir}")
                if self._preempt_guard is not None and \
                        self._preempt_guard.requested:
                    # The signal landed during eval/save: the epoch boundary
                    # just persisted IS the resume point — stop cleanly.
                    self.preempted = True
                    self.log(f"Preemption requested; stopping after epoch "
                             f"{epoch} completed")
                    return
        finally:
            if self._preempt_guard is not None:
                self._preempt_guard.uninstall()
                self._preempt_guard = None
            if mngr is not None:
                mngr.close()

    # -- benchmarking -------------------------------------------------------

    def step_flops_per_image(self, log: Optional[Callable[[str], None]] = None
                             ) -> Optional[float]:
        """FLOPs per trained image, from XLA's cost model of the compiled
        per-batch train step (augment + fwd + bwd + sync + SGD — everything
        the step really runs).  None when the backend offers no cost
        analysis — the reason is logged (``log`` overrides the trainer's
        logger, which bench.py suppresses for the print schedule).
        Used by bench.py for tflops/MFU accounting.

        ``cost_analysis()`` reports the PER-DEVICE SPMD partition, which
        processes global_batch/world images — so the divisor is the
        per-device batch, not the global batch (verified on the 8-virtual-
        device mesh: per-device flops are ~world x smaller than the
        1-device program's for the same global batch)."""
        log = log or self.log
        x = jax.ShapeDtypeStruct((self.global_batch, 32, 32, 3), jnp.uint8,
                                 sharding=self._batch_sharding)
        y = jax.ShapeDtypeStruct((self.global_batch,), jnp.int32,
                                 sharding=self._batch_sharding)
        # Compile errors propagate: this is the same program the trainer
        # runs, so a failure here is a real bug, not a missing cost model.
        comp = self.train_step.lower(
            self.state, jax.random.PRNGKey(0), x, y).compile()
        try:
            ca = comp.cost_analysis()
        except (NotImplementedError, RuntimeError) as e:
            # RuntimeError covers XlaRuntimeError(UNIMPLEMENTED) — the
            # backends-without-cost-analysis case.  Say why MFU is absent
            # instead of silently dropping every MFU field from the bench.
            log(f"MFU accounting unavailable: cost_analysis() failed "
                f"on this backend: {e!r}")
            return None
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        flops = float(ca.get("flops", 0.0)) if ca else 0.0
        if flops <= 0:
            log("MFU accounting unavailable: cost model reported "
                f"flops={flops} for the compiled train step")
            return None
        per_device_batch = self.global_batch // self.world
        return flops / per_device_batch

    def measure_phase_split(self, window_iters: int = 100,
                            windows: int = 3) -> dict:
        """The reference's fwd/bwd phase split
        (``Part 1/main.py:33-43``), window-amortized so it measures the
        chip, not the dispatch path: a forward-only scanned window and the
        full train window are timed alternately over the same staged
        batches, and backward+sync+step ≈ train − forward per iteration.

        The per-step ``profile_phases`` mode keeps the reference's exact
        per-iteration timer placement (and on the tunneled backend
        therefore reports dispatch-dominated times, as its docstring
        warns); THIS is the honest on-chip split.  Each program is timed
        at TWO window sizes (w and w/2), and the per-iteration device cost
        is the SLOPE between them — the per-dispatch fixed cost (~100 ms
        tunnel latency, which differs between the two programs and would
        otherwise contaminate the small forward) cancels exactly.  Each
        total is the best (min) of ``windows`` interleaved timings:
        contention on the shared host is one-sided, so min is the least-
        contaminated estimate (BASELINE.md 'Headline statistic').

        The defaults (W=100, 3 windows) are the configuration of the
        committed BASELINE.md artifact; tools/perf_phase_split.py
        reproduces it.

        The train windows apply REAL optimizer updates while timing (the
        timed program must be the training program); the pre-measurement
        TrainState is snapshotted and restored on return, so measuring
        mid-training does not perturb the trajectory."""
        if self.host_augment:
            raise ValueError(
                "measure_phase_split times the compiled windowed path "
                "(device-side transform); it does not support "
                "host_augment=True — construct a separate Trainer for "
                "the phase split")
        key = jax.random.PRNGKey(self.seed)
        epoch_images, epoch_labels, _ = self._stage_train_epoch(0)
        nbatches = epoch_images.shape[0]
        if nbatches == 0:
            raise ValueError("measure_phase_split needs at least one full "
                             "global batch")
        w = min(window_iters, nbatches)
        half = max(w // 2, 1)
        if w == half:
            raise ValueError("measure_phase_split needs window_iters >= 2 "
                             "for the two-size slope")
        if self._fwd_window is None:   # jit caches are per function object
            self._fwd_window = steplib.make_fwd_window(
                self.apply_fn, self.mesh,
                single=self.strategy_name == "single",
                augment=self.augment, compute_dtype=self.compute_dtype)
        fwd_window = self._fwd_window
        # Deep-copy the state: train_window DONATES its input buffers, so
        # the original arrays are consumed during measurement — the copy is
        # what lets the trajectory be restored afterwards.
        state_snapshot = jax.tree.map(jnp.copy, self.state)
        lengths = {n: jnp.zeros((n,), jnp.int8) for n in (w, half)}
        # Warm both programs at both sizes (compiles excluded from timers).
        for n in (w, half):
            np.asarray(fwd_window(self.state, key, epoch_images,
                                  epoch_labels, jnp.int32(0), lengths[n]))
            out = self.train_window(
                self.state, key, epoch_images, epoch_labels, jnp.int32(0),
                lengths[n])
            self.state, losses = out[0], out[1]  # tolerate guarded arity
            np.asarray(losses)
        totals = {("fwd", w): [], ("fwd", half): [],
                  ("step", w): [], ("step", half): []}
        for i in range(windows):
            start = jnp.int32((i % max(nbatches // w, 1)) * w)
            for n in (w, half):
                t0 = time.time()
                np.asarray(fwd_window(self.state, key, epoch_images,
                                      epoch_labels, start, lengths[n]))
                totals[("fwd", n)].append(time.time() - t0)
                t0 = time.time()
                out = self.train_window(
                    self.state, key, epoch_images, epoch_labels, start,
                    lengths[n])
                self.state, losses = out[0], out[1]
                np.asarray(losses)  # value fetch = completion fence
                totals[("step", n)].append(time.time() - t0)
        self.state = state_snapshot   # measurement leaves no training trace
        span = w - half
        mins_ms = {f"{prog}_{n}": min(ts) * 1e3
                   for (prog, n), ts in totals.items()}
        fwd_ms = (mins_ms[f"fwd_{w}"] - mins_ms[f"fwd_{half}"]) / span
        step_ms = (mins_ms[f"step_{w}"] - mins_ms[f"step_{half}"]) / span
        return {"window_iters": w, "windows": windows,
                "forward_ms_per_iter": fwd_ms,
                "step_ms_per_iter": step_ms,
                "backward_ms_per_iter": step_ms - fwd_ms,
                "dispatch_ms_fwd_window": mins_ms[f"fwd_{w}"] - fwd_ms * w,
                "dispatch_ms_step_window": (
                    mins_ms[f"step_{w}"] - step_ms * w),
                # Raw min totals (ms) so callers can aggregate mins ACROSS
                # calls — a single contended half-window min makes the
                # within-call slope misleading (even negative); the
                # across-trials slope is the robust estimate
                # (tools/perf_phase_split.py).
                "window_totals_ms": mins_ms}

    def steady_state_throughput(self, max_iters: int = 3 * WINDOW,
                                window_iters=None) -> Tuple[float, float]:
        """(images/sec, images/sec/chip) over steady-state iterations,
        using the reference's measurement design: windowed dispatches, the
        first window (compile+warmup) excluded.

        ``window_iters`` sets the iterations per compiled dispatch:
        ``"epoch"`` = the whole epoch per dispatch (what bench.py uses on
        TPU), an int = that many, None = min(epoch, max(max_iters, WINDOW)).
        Windows LARGER than the reference's 20-iteration reporting window
        are deliberate: each dispatch through the tunneled TPU backend
        costs ~100 ms of host-side latency regardless of size (measured;
        tools/perf_pieces.py), which at 20-iter windows would measure the
        tunnel, not the chip (~51k vs ~88k img/s at the headline config).
        The reference-parity path (train_model) keeps the 20-iteration
        granularity for its print schedule; documented in BASELINE.md."""
        if self.host_augment:
            raise ValueError(
                "steady_state_throughput measures the compiled windowed "
                "path (device-side transform); it does not support "
                "host_augment=True — construct a separate Trainer for "
                "throughput measurement")
        key = jax.random.PRNGKey(self.seed)
        epoch_images, epoch_labels, _ = self._stage_train_epoch(0)
        nbatches = epoch_images.shape[0]
        if nbatches == 0:
            raise ValueError(
                "steady_state_throughput needs at least one full global "
                f"batch ({self.global_batch}); the dataset holds only a "
                "ragged tail")
        if window_iters == "epoch":
            w = nbatches
        else:
            w = min(window_iters or max(max_iters, WINDOW), nbatches)
        length_arr = jnp.zeros((w,), jnp.int8)
        nwin = max(2, -(-max_iters // w))
        starts = [i * w for i in range(max(nbatches // w, 1))] or [0]

        # Per-window keys, FOLDED AHEAD OF the timed region: when the start
        # offsets wrap around a small epoch, the same batches get fresh
        # augmentation randomness instead of replaying the previous pass's
        # stream — but a host-side fold_in between dispatches would break
        # the back-to-back window chain with a tiny interleaved program
        # (~6% throughput on v5e), so all keys are materialized up front.
        keys = [jax.device_put(k) for k in
                jax.random.split(key, nwin + 1)]
        for k in keys:
            np.asarray(k)  # value fetch: keep transfers out of timed region

        def dispatch(start, wi):
            out = self.train_window(
                self.state, keys[wi], epoch_images,
                epoch_labels, jnp.int32(start), length_arr)
            self.state, losses = out[0], out[1]  # tolerate guarded arity
            return losses

        # Window 0: compile + warmup (excluded, as the reference excludes its
        # first 20-iteration window).  Fetching the losses is the fence.
        _ = np.asarray(dispatch(0, 0))
        # Steady state: windows dispatch back-to-back — the state pytree
        # chains every step sequentially on device — and all losses are
        # fetched after the last window, which transitively fences the whole
        # chain.  (train_model, the reference-parity path, syncs per window
        # to print; the bench measures device throughput.)
        t0 = time.time()
        pending = []
        for i in range(nwin):
            pending.append(dispatch(starts[(1 + i) % len(starts)], 1 + i))
        for losses in pending:
            _ = np.asarray(losses)
        elapsed = time.time() - t0
        ips = self.global_batch * w * nwin / elapsed
        return ips, ips / self.world
