"""Training driver: the reference's ``run``/``train_model``/``test_model``
(``/root/reference/src/Part 2a/main.py:19-68,71-114,130-145``) rebuilt around
one compiled SPMD step.

Differences from the reference, by design (all documented in BASELINE.md):

  * one process drives all local devices; "workers" are mesh positions, and
    each mesh position sees exactly the shard the reference's
    DistributedSampler would hand that rank (data.sharding);
  * the per-batch phases (augment/forward/loss/backward/sync/step) are one
    XLA program — timing therefore reports the fused step time, fenced by
    fetching the loss values (under the tunneled TPU backend
    ``block_until_ready`` can return before computation completes); an
    optional split-phase mode additionally times a forward-only program
    for the reference's fwd/bwd split;
  * the ragged final train batch (drop_last=False) runs through a second
    compiled step at its true static shape — exact short-batch BN/CE
    semantics, same iteration count as the reference;
  * evaluation runs once across the mesh (psum'd counts) instead of
    redundantly per rank, reporting identical quantities.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from typing import Callable, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import models as model_zoo
from ..data import cifar10, native, sharding
from ..obs import NULL, git_sha
from ..ops import sgd
from ..parallel import get_strategy, mesh as meshlib
from ..utils.metrics import WINDOW, WindowedTimers
from . import step as steplib

GLOBAL_BATCH = 256      # reference: batch_size=256 (Part 2a/main.py:173)
SEED = 0                # reference: torch.manual_seed(0) (main.py:80-81)


def _shard_batch_cols(n_examples: int, world: int, global_batch: int,
                      epoch: int, *, shuffle: bool, seed: int = SEED,
                      reshuffle_each_epoch: bool = False
                      ) -> Iterator[np.ndarray]:
    """Yield each global batch's device-major index columns (the sampler
    layout ``_shard_batches`` materializes).  The chunked staging producer
    consumes the RAW indices so the fused C++ gather+augment
    (native.gather_augment_u8) can write arena rows straight from the
    resident dataset, with no intermediate gathered batch."""
    per = global_batch // world
    idx = sharding.global_epoch_indices(
        n_examples, world, seed=seed, shuffle=shuffle, epoch=epoch,
        reshuffle_each_epoch=reshuffle_each_epoch)
    nfull = idx.shape[1] // per
    for b in range(nfull):
        yield idx[:, b * per:(b + 1) * per].reshape(-1)  # device-major
    if idx.shape[1] % per:
        yield idx[:, nfull * per:].reshape(-1)


def _shard_batches(split: cifar10.Split, world: int, global_batch: int,
                   epoch: int, *, shuffle: bool, seed: int = SEED,
                   reshuffle_each_epoch: bool = False
                   ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Yield [global_batch,...] host arrays laid out so that sharding dim 0
    over the mesh gives device d exactly sampler-rank d's examples.

    The final yield may be SHORT (the ragged tail): the reference's
    DataLoader uses ``drop_last=False`` (``Part 1/main.py:96-101``), so the
    short 196th/782nd batch is trained too.  The sampler's wrap-padding
    guarantees every rank holds the same per-rank count, so the tail is
    equal-sized across ranks and shards cleanly; it runs through a second
    compiled step at its own (static) shape — exact short-batch BN/CE
    semantics, no masking."""
    for cols in _shard_batch_cols(
            len(split.labels), world, global_batch, epoch, shuffle=shuffle,
            seed=seed, reshuffle_each_epoch=reshuffle_each_epoch):
        # Batch assembly via the native threaded gather (the reference's
        # DataLoader-worker equivalent); falls back to numpy fancy indexing.
        yield native.gather(split.images, cols), split.labels[cols]


def _eval_batches(split: cifar10.Split, global_batch: int
                  ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Full test set in order, final batch padded with label -1 sentinels
    (masked in the eval step) so every batch keeps the compiled shape."""
    n = len(split.labels)
    for start in range(0, n, global_batch):
        imgs = split.images[start:start + global_batch]
        labs = split.labels[start:start + global_batch]
        if len(labs) < global_batch:
            pad = global_batch - len(labs)
            imgs = np.concatenate([imgs, np.zeros((pad, 32, 32, 3), np.uint8)])
            labs = np.concatenate([labs, np.full((pad,), -1, np.int32)])
        yield imgs, labs


class Trainer:
    """Wires data + model + strategy + mesh into the reference's run()."""

    def __init__(self, model: str = "vgg11", strategy: str = "allreduce",
                 *, mesh=None, num_devices: Optional[int] = None,
                 global_batch: int = GLOBAL_BATCH, data_dir: str = "./data",
                 seed: int = SEED, augment: bool = True,
                 sgd_cfg: sgd.SGDConfig = sgd.SGDConfig(),
                 profile_phases: bool = False,
                 host_augment: bool = False,
                 host_chunks: int = 4,
                 precision: str = "f32",
                 reshuffle_each_epoch: bool = False,
                 limit_train_batches: Optional[int] = None,
                 limit_eval_batches: Optional[int] = None,
                 log: Callable[[str], None] = print,
                 telemetry=NULL):
        self.mesh = mesh if mesh is not None else meshlib.make_mesh(num_devices)
        self.world = self.mesh.devices.size
        if global_batch % self.world:
            raise ValueError(f"global batch {global_batch} not divisible by "
                             f"world size {self.world}")
        self.global_batch = global_batch
        self.log = log
        # Structured telemetry recorder (obs/) — NULL (a stateless no-op)
        # by default, so the disabled path writes no files and allocates
        # nothing per step; the stdout print schedule above/below is the
        # reference-parity surface either way and is never redirected.
        self.telemetry = telemetry
        self.profile_phases = profile_phases
        # host_augment: the train transform runs in the C++ host pipeline
        # (data/native.py fl_augment_f32 — the reference's DataLoader-worker
        # model, Part 1/main.py:96-101) and the step receives preprocessed
        # f32 batches.  Since round 5 this dispatches scanned WINDOWS over
        # producer-staged buffers (_train_model_host_windowed — the
        # reference's own num_workers=2 + batching amortization); the
        # per-batch dispatch path remains under profile_phases.  The
        # default (False) keeps the TPU-first design: uint8 to the device,
        # transform fused into the compiled step.
        self.host_augment = host_augment
        # host_chunks: the windowed host-augment path stages each WINDOW as
        # K sub-window chunks put_global'd individually by the producer, so
        # window w+1's transfers overlap window w's device compute (round 6;
        # the round-5 path shipped ONE blocking whole-window put and left
        # the host->device link idle during compute — BASELINE.md pinned
        # that 21% short of target).  K=1 degrades exactly to round 5's
        # whole-window staging; default 4 keeps chunks ~5 batches (~3.8 MiB
        # at B=256) — deep enough to overlap, coarse enough that per-put
        # fixed costs stay amortized (bench.py chunk_sweep measures K).
        if host_chunks < 1:
            raise ValueError(f"host_chunks must be >= 1, got {host_chunks}")
        self.host_chunks = int(host_chunks)
        # Compute precision: "f32" (reference parity, the default) or "bf16"
        # (mixed precision: f32 master weights/optimizer/BN statistics/loss,
        # bf16 conv+matmul activations — the MXU's native mode).
        if precision not in ("f32", "bf16"):
            raise ValueError(f"precision must be 'f32' or 'bf16', "
                             f"got {precision!r}")
        self.precision = precision
        self.compute_dtype = compute_dtype = (
            jnp.bfloat16 if precision == "bf16" else None)
        self.augment = augment
        self.seed = seed
        # The reference never reshuffles across epochs (no sampler.set_epoch
        # call — SURVEY.md C6); opt in for proper per-epoch reshuffling.
        self.reshuffle_each_epoch = reshuffle_each_epoch
        # Optional iteration caps (None = full splits, the reference's
        # behavior): bound epoch cost for smoke runs and benchmarks.
        for name, lim in (("limit_train_batches", limit_train_batches),
                          ("limit_eval_batches", limit_eval_batches)):
            if lim is not None and lim < 1:
                raise ValueError(f"{name} must be >= 1, got {lim}")
        self.limit_train_batches = limit_train_batches
        self.limit_eval_batches = limit_eval_batches

        # Split-replacement generations: staging caches key on these, so
        # swapping a split always restages (id() reuse after GC cannot serve
        # stale device arrays).  Must exist before the property assignments.
        self._train_gen = 0
        self._test_gen = 0
        self.train_split, self.test_split, self.real_data = cifar10.load(data_dir)
        # Reference parity: these lines print len(train_loader) — the
        # per-rank BATCH count, not the example count (Part 2a/main.py:46,55).
        def ceil_div(a, b):
            return -(-a // b)

        per_rank_samples = ceil_div(len(self.train_split.labels), self.world)
        per_rank_batch = global_batch // self.world
        # The printed count is ceil (DataLoader drop_last=False parity, 782
        # at 50000/64) and matches the trained count: the ragged final batch
        # runs through its own compiled step at its true shape (_shard_batches
        # docstring), so printed == trained.
        self.log(f"Size of training set is "
                 f"{ceil_div(per_rank_samples, per_rank_batch)}")
        # The reference's test loader uses the PER-RANK batch (256/world,
        # Part 2a/main.py:50-54) over the UNsharded 10k test set, so its
        # printed size is ceil(10000/(256/world)).
        self.log(f"Size of test set is "
                 f"{ceil_div(len(self.test_split.labels), per_rank_batch)}")

        # `model` is a registry name ("vgg11", "resnet18", ...) or a custom
        # (init_fn, apply_fn) pair (used by tests to keep compiles small).
        if isinstance(model, str):
            self.model_name = model
            init_fn, self.apply_fn = model_zoo.get_model(model)
        else:
            self.model_name = "custom"
            init_fn, self.apply_fn = model
        self.state = steplib.init_train_state(
            init_fn, jax.random.PRNGKey(seed))
        # Commit the state to the mesh (replicated) up front: otherwise the
        # first windowed call sees uncommitted arrays and the second call a
        # different sharding signature -> a full recompile.  put_global_tree
        # keeps this correct when the mesh spans multiple processes.
        self.state = meshlib.put_global_tree(
            self.state, meshlib.replicated(self.mesh))
        self.strategy_name = strategy
        self.sgd_cfg = sgd_cfg
        strat = get_strategy(strategy)
        self.train_step = steplib.make_train_step(
            self.apply_fn, strat, self.mesh, sgd_cfg, augment=augment,
            compute_dtype=compute_dtype)
        self.train_window = steplib.make_train_window(
            self.apply_fn, strat, self.mesh, sgd_cfg, augment=augment,
            compute_dtype=compute_dtype)
        if host_augment:
            self.train_step_host = steplib.make_train_step(
                self.apply_fn, strat, self.mesh, sgd_cfg, augment="host",
                compute_dtype=compute_dtype)
            # The windowed host path ships COMPACT uint8 (the C++ pipeline
            # does the stochastic crop/flip; the affine normalize fuses
            # into the device step, augment=False = normalize-only): the
            # host->device link is the path's roofline (BASELINE.md), and
            # uint8 carries 4x fewer bytes than the f32 per-step format.
            self.train_window_host = steplib.make_train_window(
                self.apply_fn, strat, self.mesh, sgd_cfg, augment=False,
                compute_dtype=compute_dtype)
        self.eval_window = steplib.make_eval_window(
            self.apply_fn, self.mesh, compute_dtype=compute_dtype)
        if profile_phases:
            self._fwd_only = self._make_fwd_only()

        self._batch_sharding = meshlib.batch_sharding(self.mesh)
        from jax.sharding import NamedSharding, PartitionSpec as P
        self._epoch_sharding = NamedSharding(self.mesh, P(None, meshlib.DATA_AXIS))
        if host_augment:
            # On-device window assembly for the chunked staging path: ONE
            # jitted concatenate over the K device-resident chunks (shared
            # by images and labels; retraced per distinct arity/shape).  The
            # u8 window copy it performs is ~15.7 MiB at W=20/B=256 —
            # microseconds of HBM bandwidth against the link's ~15 ms/batch
            # budget.  NEGATIVE RESULT (the rejected assembly variant):
            # dispatching the scanned window per-chunk — or scanning across
            # the chunk list — pays the tunneled backend's ~100 ms fixed
            # dispatch latency PER CHUNK (measured: tools/perf_pieces.py,
            # BASELINE.md "dispatch floor"), i.e. K x the cost round 5's
            # windowing exists to amortize; and a K-argument fused
            # scan-over-chunks program recompiles per distinct chunk-count
            # signature while still serializing the window on its LAST
            # chunk's arrival.  Concatenate-then-scan keeps one dispatch
            # per window and lets earlier chunks transfer while the
            # previous window computes.
            self._assemble_chunks = jax.jit(
                lambda *chunks: jnp.concatenate(chunks, axis=0),
                out_shardings=self._epoch_sharding)
        self._staging_arena = None          # lazily-built native.StagingArena
        self._staging_put_copies = None     # backend aliasing probe result
        self._staged_train = None   # (epoch_images, epoch_labels, tail)
        self._staged_eval = None
        self._fwd_window = None     # built lazily by measure_phase_split
        self._warmed_tail_shapes = set()
        self._warmed_window_shapes = set()
        self.last_epoch_timers: Optional[WindowedTimers] = None
        self._collective_stats_emitted = False

        if telemetry.enabled:
            d0 = self.mesh.devices.flat[0]
            telemetry.write_manifest({
                "model": self.model_name,
                "strategy": self.strategy_name,
                "world_size": self.world,
                "global_batch": global_batch,
                "precision": precision,
                "augment": augment,
                "host_augment": host_augment,
                "host_chunks": host_chunks,
                "profile_phases": profile_phases,
                "seed": seed,
                "reshuffle_each_epoch": reshuffle_each_epoch,
                "real_data": self.real_data,
                "lr": sgd_cfg.lr, "momentum": sgd_cfg.momentum,
                "weight_decay": sgd_cfg.weight_decay,
                "jax_version": jax.__version__,
                "backend": jax.default_backend(),
                "device_kind": getattr(d0, "device_kind", str(d0)),
                "num_devices": self.world,
                # The native host loader degrades SILENTLY to NumPy; the
                # manifest records whether this run really had the C++
                # pipeline, and if not, why (data/native.py load_error).
                "native_loader": {"available": native.available(),
                                  "error": native.load_error()},
                "git_sha": git_sha(),
            })

    # -- telemetry helpers ---------------------------------------------------

    def _emit_device_gauges(self, epoch: int) -> None:
        """Per-device ``memory_stats()`` gauges (backends without the API —
        CPU — contribute nothing)."""
        for d in self.mesh.devices.flat:
            ms = getattr(d, "memory_stats", None)
            if ms is None:
                continue
            try:
                stats = ms()
            except Exception:
                continue
            if not stats:
                continue
            keep = {k: stats[k] for k in
                    ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                     "largest_alloc_size") if k in stats} or dict(stats)
            self.telemetry.gauge("device_memory", keep, device=int(d.id),
                                 epoch=epoch)

    def _emit_collective_telemetry(self) -> None:
        """Counters/gauges for the compiled train step's collective pattern
        (utils/hlo_stats over the pre-optimization HLO): op counts, result
        bytes and chain depth — the static cost shape of the gradient-sync
        tier, attached to the run artifact.  Best-effort: backends that
        cannot produce the HLO print contribute an error gauge instead."""
        if self._collective_stats_emitted:
            return
        self._collective_stats_emitted = True
        from ..utils import hlo_stats
        try:
            x = jax.ShapeDtypeStruct(
                (self.global_batch, 32, 32, 3),
                jnp.float32 if self.host_augment else jnp.uint8,
                sharding=self._batch_sharding)
            y = jax.ShapeDtypeStruct((self.global_batch,), jnp.int32,
                                     sharding=self._batch_sharding)
            step_fn = self.train_step_host if self.host_augment \
                else self.train_step
            txt = step_fn.lower(
                self.state, jax.random.PRNGKey(0), x, y) \
                .compiler_ir(dialect="hlo").as_hlo_text()
        except Exception as e:
            self.telemetry.gauge("collective_stats_error", repr(e))
            return
        stats = hlo_stats.collective_stats(txt)
        for op, entry in stats["ops"].items():
            self.telemetry.counter(f"collective_{op}_count", entry["count"])
            self.telemetry.counter(f"collective_{op}_result_mib",
                                   entry["result_mib"])
        self.telemetry.gauge(
            "collective_totals", {
                "total_count": stats["total_count"],
                "total_result_mib": stats["total_result_mib"],
                "chain_depth": hlo_stats.collective_chain_depth(txt)})

    # -- dataset splits (generation-tracked for staging-cache keys) ---------

    @property
    def train_split(self) -> cifar10.Split:
        return self._train_split

    @train_split.setter
    def train_split(self, split: cifar10.Split) -> None:
        self._train_split = split
        self._train_gen += 1

    @property
    def test_split(self) -> cifar10.Split:
        return self._test_split

    @test_split.setter
    def test_split(self, split: cifar10.Split) -> None:
        self._test_split = split
        self._test_gen += 1

    # -- device placement ---------------------------------------------------

    def _put(self, images: np.ndarray, labels: np.ndarray):
        return (meshlib.put_global(images, self._batch_sharding),
                meshlib.put_global(np.asarray(labels, np.int32),
                                   self._batch_sharding))

    def _make_fwd_only(self):
        from jax.sharding import PartitionSpec as P
        try:
            from jax import shard_map
        except ImportError:              # jax < 0.6: experimental namespace
            from jax.experimental.shard_map import shard_map
        from ..data import augment as aug
        from ..ops.loss import cross_entropy
        from ..parallel.mesh import DATA_AXIS
        from jax import lax

        from ..train.step import _SHARD_MAP_KW, maybe_cast

        def body(params, bn_state, images, labels):
            # host_augment feeds preprocessed f32; otherwise normalize here.
            x = images if self.host_augment else aug.normalize(images)
            x = maybe_cast(x, self.compute_dtype)
            logits, _ = self.apply_fn(params, bn_state, x, train=True)
            return lax.pmean(cross_entropy(logits, labels), DATA_AXIS)

        mapped = shard_map(body, mesh=self.mesh,
                           in_specs=(P(), P(), P(DATA_AXIS), P(DATA_AXIS)),
                           out_specs=P(), **_SHARD_MAP_KW)
        return jax.jit(mapped)

    # -- on-device staging --------------------------------------------------

    def _stage_train_epoch(self, epoch: int):
        """Stage the whole epoch's batches on device: full batches as
        [NB, B, ...] arrays plus the ragged tail batch (or None) separately.

        One host->device transfer per epoch instead of one per batch —
        transfers carry a large fixed cost, and the uint8 epoch is ~150 MB.
        With the reference's never-reshuffled sampler (C6) the staging is
        reused across epochs; the cache is keyed on the split GENERATION
        (bumped by the train_split setter) and (when reshuffling) the epoch,
        so replacing ``train_split`` or enabling reshuffle restages.
        """
        cache_key = (self._train_gen,
                     epoch if self.reshuffle_each_epoch else 0)
        if self._staged_train is not None and \
                self._staged_train[0] == cache_key:
            return self._staged_train[1]
        imgs, labs = [], []
        tail = None
        for i, l in _shard_batches(
                self.train_split, self.world, self.global_batch, epoch,
                shuffle=True, seed=self.seed,
                reshuffle_each_epoch=self.reshuffle_each_epoch):
            if i.shape[0] < self.global_batch:   # ragged tail (always last)
                tail = (meshlib.put_global(i, self._batch_sharding),
                        meshlib.put_global(l.astype(np.int32),
                                           self._batch_sharding))
                break
            imgs.append(i)
            labs.append(l)
            if self.limit_train_batches is not None and \
                    len(imgs) >= self.limit_train_batches:
                break
        if imgs:
            full = (meshlib.put_global(np.stack(imgs), self._epoch_sharding),
                    meshlib.put_global(np.stack(labs).astype(np.int32),
                                       self._epoch_sharding))
        else:  # dataset smaller than one global batch: tail-only epoch
            full = (meshlib.put_global(
                        np.zeros((0, self.global_batch, 32, 32, 3), np.uint8),
                        self._epoch_sharding),
                    meshlib.put_global(
                        np.zeros((0, self.global_batch), np.int32),
                        self._epoch_sharding))
        staged = (full[0], full[1], tail)
        self._staged_train = (cache_key, staged)
        return staged

    def _warm_train_windows(self, staged):
        """AOT-compile the 20-iteration window shapes train_model will
        dispatch (full WINDOW and the ragged window) so mid-epoch compiles
        never pollute the timers — the windowed analogue of the reference's
        first-window warmup exclusion.  Called from train_model, NOT from
        staging: the bench path stages epochs but dispatches epoch-length
        windows (whose compile lands in its own excluded warmup window), and
        would pay these compiles dead.  Idempotent per shape."""
        epoch_images, epoch_labels, _ = staged
        nbatches = epoch_images.shape[0]
        key = jax.random.PRNGKey(self.seed)
        for w in self._window_shape_set(nbatches):
            cache_key = (w, tuple(epoch_images.shape))
            if cache_key in self._warmed_window_shapes:
                continue
            with self.telemetry.span("compile_warmup",
                                     program="train_window", window=w):
                self.train_window.lower(
                    self.state, key, epoch_images, epoch_labels,
                    jnp.int32(0), jnp.zeros((w,), jnp.int8)).compile()
            self._warmed_window_shapes.add(cache_key)

    def _warm_tail_step(self, tail) -> None:
        """AOT-compile the tail-shape train step (idempotent per shape) so
        the ragged batch's compile never lands inside a timed iteration.
        Deliberately NOT done at staging time: the bench path stages epochs
        but never trains the tail, and would pay a dead compile."""
        cache_key = (tail[0].shape[0], str(tail[0].dtype))
        if cache_key in self._warmed_tail_shapes:
            return
        with self.telemetry.span("compile_warmup", program="train_step_tail",
                                 batch=int(tail[0].shape[0])):
            self.train_step.lower(
                self.state, jax.random.PRNGKey(self.seed), *tail).compile()
        self._warmed_tail_shapes.add(cache_key)

    def _stage_eval(self):
        cache_key = self._test_gen
        if self._staged_eval is not None and \
                self._staged_eval[0] == cache_key:
            return self._staged_eval[1]
        imgs, labs = [], []
        for i, l in _eval_batches(self.test_split, self.global_batch):
            imgs.append(i)
            labs.append(l.astype(np.int32))
            if self.limit_eval_batches is not None and \
                    len(imgs) >= self.limit_eval_batches:
                break
        staged = (meshlib.put_global(np.stack(imgs), self._epoch_sharding),
                  meshlib.put_global(np.stack(labs), self._epoch_sharding))
        self._staged_eval = (cache_key, staged)
        return staged

    # -- reference-parity loops --------------------------------------------

    def train_model(self, epoch: int) -> WindowedTimers:
        """One training epoch with the reference's print/timing schedule.

        Default mode runs one compiled dispatch per 20-iteration window
        (lax.scan inside), timed with value-fetch fences — the same
        granularity the reference reports at.  ``profile_phases=True``
        switches to the per-step path, which additionally times a
        forward-only program to report the reference's fwd/bwd split.
        """
        if self.profile_phases:
            return self._train_model_per_step(epoch)
        if self.host_augment:
            return self._train_model_host_windowed(epoch)
        if self.telemetry.enabled:
            self._emit_collective_telemetry()
        timers = WindowedTimers(self.log, telemetry=self.telemetry,
                                epoch=epoch)
        key = jax.random.fold_in(jax.random.PRNGKey(self.seed), epoch)
        staged = self._stage_train_epoch(epoch)
        self._warm_train_windows(staged)
        epoch_images, epoch_labels, tail = staged
        nbatches = epoch_images.shape[0]
        start = 0
        while start < nbatches:
            w = min(WINDOW, nbatches - start)
            t0 = time.time()
            self.state, losses = self.train_window(
                self.state, key, epoch_images, epoch_labels,
                jnp.int32(start), jnp.zeros((w,), jnp.int8))
            losses = np.asarray(losses)  # value fetch = completion fence
            per_iter = (time.time() - t0) / w
            for loss in losses:
                timers.record(float(loss), per_iter)
            start += w
        if tail is not None:
            # The ragged final batch (drop_last=False parity) through its
            # own compiled step; host-side fold of the batch index keeps the
            # canonical (index, position) key order of both other paths.
            self._warm_tail_step(tail)  # keep the compile out of the timer
            tail_key = jax.random.fold_in(key, nbatches)
            t0 = time.time()
            self.state, loss = self.train_step(self.state, tail_key, *tail)
            loss = float(loss)  # value fetch = completion fence
            # steady=False: this lone per-dispatch sample carries the fixed
            # dispatch latency the amortized window samples do not.
            timers.record(loss, time.time() - t0, steady=False)
        self.last_epoch_timers = timers
        return timers

    def _train_model_per_step(self, epoch: int) -> WindowedTimers:
        """Per-batch dispatch path: the fwd/bwd phase split
        (``profile_phases``) and/or the host-side augmentation pipeline
        (``host_augment`` — per-batch host work is the point of that mode,
        exactly like the reference's DataLoader workers, so it is
        double-buffered the way theirs is: batch k+1 prepares on a
        producer thread while step k runs, ``_iter_host_batches``)."""
        if self.telemetry.enabled:
            self._emit_collective_telemetry()
        timers = WindowedTimers(self.log, telemetry=self.telemetry,
                                epoch=epoch)
        key = jax.random.fold_in(jax.random.PRNGKey(self.seed), epoch)
        step_fn = self.train_step_host if self.host_augment \
            else self.train_step
        self._warm_per_step_tail_shapes()
        if self.host_augment:
            batches = self._iter_host_batches(epoch)
        else:
            batches = ((it, *self._put(imgs, labs))
                       for it, (imgs, labs) in enumerate(_shard_batches(
                           self.train_split, self.world, self.global_batch,
                           epoch, shuffle=True, seed=self.seed,
                           reshuffle_each_epoch=self.reshuffle_each_epoch)))
            if self.limit_train_batches is not None:
                batches = itertools.islice(batches, self.limit_train_batches)
        for it, x, y in batches:
            step_key = jax.random.fold_in(key, it)
            fwd_time = None
            if self.profile_phases:
                t0 = time.time()
                # np.asarray (a real value fetch) is the fence: under the
                # tunneled TPU backend block_until_ready can return before
                # the computation finishes — that would time dispatch only.
                np.asarray(self._fwd_only(
                    self.state.params, self.state.bn_state, x, y))
                fwd_time = time.time() - t0
            t0 = time.time()
            self.state, loss = step_fn(self.state, step_key, x, y)
            loss = float(loss)  # value fetch = completion fence
            # The fused step contains its own forward; the separately-timed
            # forward-only program is ONLY used to report the reference's
            # fwd/bwd split (backward ≈ fused − forward) and is excluded
            # from the step time so totals aren't inflated.
            step_time = time.time() - t0
            timers.record(loss, step_time, fwd_time)
        self.last_epoch_timers = timers
        return timers

    def _host_aug_params(self, n: int, epoch: int, it: int):
        """The counter-based host augmentation stream: deterministic in
        (seed, epoch, iteration) — the analogue of the device path's
        fold_in chain (a different stream, same contract), and the reason
        ALL host-augment execution paths (per-step f32, windowed uint8)
        consume bit-identical crops/flips regardless of thread or dispatch
        timing."""
        rng = np.random.default_rng([self.seed, epoch, it])
        return (rng.integers(0, 9, (n, 2), dtype=np.int32),
                rng.integers(0, 2, (n,), dtype=np.uint8))

    def _host_transform(self, imgs: np.ndarray, n: int, epoch: int,
                        it: int) -> np.ndarray:
        """C++ host-pipeline transform, f32 out (the per-step format: the
        reference DataLoader's ToTensor+Normalize product)."""
        if self.augment:
            return native.augment(imgs, *self._host_aug_params(n, epoch, it))
        return native.normalize(imgs)

    def _host_transform_u8(self, imgs: np.ndarray, n: int, epoch: int,
                           it: int) -> np.ndarray:
        """C++ host-pipeline transform, uint8 out (the windowed staging
        format: same crop/flip stream as ``_host_transform``, normalize
        deferred to the device step — 4x fewer bytes over the link)."""
        if self.augment:
            return native.augment_u8(imgs,
                                     *self._host_aug_params(n, epoch, it))
        return imgs

    def _put_host_augmented(self, imgs: np.ndarray, labs: np.ndarray,
                            epoch: int, it: int):
        """Host-transform one batch and place the resulting f32 batch.

        Runs on the prefetch producer thread; the telemetry span stack is
        thread-local, so these spans nest correctly there."""
        with self.telemetry.span("host_augment"):
            xh = self._host_transform(imgs, len(labs), epoch, it)
        with self.telemetry.span("prefetch_put"):
            return (meshlib.put_global(xh, self._batch_sharding),
                    meshlib.put_global(np.asarray(labs, np.int32),
                                       self._batch_sharding))

    # Prefetched batches queued ahead of the consumer: 2 = one in flight on
    # the producer thread plus one ready — the reference's num_workers=2
    # DataLoader keeps the same depth of completed batches ahead.
    PREFETCH_DEPTH = 2

    def _prefetch_iter(self, fill, depth: Optional[int] = None):
        """Producer-thread prefetch scaffolding shared by both host-augment
        paths: runs ``fill(emit)`` on a daemon thread — ``emit(item)``
        enqueues and returns False once the consumer has gone away — and
        yields the emitted items in order.  ``depth`` overrides the queue
        bound (the chunked windowed path queues per-CHUNK items, so its
        bound is two windows' worth of chunks rather than two windows).
        Every producer exit path enqueues a sentinel (BaseException
        included) so the consumer can never block forever; the consumer
        polls with a timeout and drains the queue before declaring a dead
        producer sentinel-less."""
        q: queue.Queue = queue.Queue(maxsize=depth or self.PREFETCH_DEPTH)
        stop = threading.Event()

        def safe_put(item) -> bool:
            """Enqueue unless the consumer has gone away."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.2)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                fill(lambda item: safe_put(("item", item)))
                safe_put(("done", None))
            except BaseException as e:  # noqa: BLE001 — every exit path
                # must enqueue a sentinel or the consumer would block on an
                # empty queue forever; surfaced (and re-raised) there.
                safe_put(("err", e))

        t = threading.Thread(target=produce, daemon=True,
                             name="host-augment-prefetch")
        t.start()
        try:
            while True:
                if self.telemetry.enabled:
                    # Depth BEFORE the blocking get: 0 here means the
                    # consumer is about to stall on the producer — the
                    # pipeline-health signal this gauge exists for.
                    self.telemetry.gauge("prefetch_queue_depth", q.qsize())
                try:
                    kind, payload = q.get(timeout=1.0)
                except queue.Empty:
                    if t.is_alive():
                        continue
                    # Producer exited; its final put may have raced our
                    # timeout, so drain non-blockingly before declaring it
                    # died without a sentinel (only then fail loudly
                    # instead of hanging).
                    try:
                        kind, payload = q.get_nowait()
                    except queue.Empty:
                        raise RuntimeError(
                            "host-augment prefetch thread exited without "
                            "delivering a batch or a completion sentinel")
                if kind == "done":
                    break
                if kind == "err":
                    raise payload
                yield payload
        finally:
            stop.set()
            t.join(timeout=10)
            if t.is_alive():
                self.log("warning: host-augment prefetch thread did not "
                         "exit within 10s")

    def _iter_host_batches(self, epoch: int):
        """Double-buffered host-augment pipeline: yields ``(it, x, y)`` with
        batch k+1 gathered, C++-augmented and device-put on a producer
        thread while step k runs on device — the reference's
        DataLoader-worker overlap (``Part 1/main.py:96-101``), which the
        previously-serial per-step path lacked (VERDICT r3 item 6).

        The host RNG stream is counter-based in (seed, epoch, it)
        (``_host_transform``), so the prefetched stream is BIT-IDENTICAL
        to the serial one regardless of thread timing — pinned by
        tests/test_cli_and_profiling.py."""
        def fill(emit):
            for it, (imgs, labs) in enumerate(_shard_batches(
                    self.train_split, self.world, self.global_batch,
                    epoch, shuffle=True, seed=self.seed,
                    reshuffle_each_epoch=self.reshuffle_each_epoch)):
                if self.limit_train_batches is not None and \
                        it >= self.limit_train_batches:
                    break
                if not emit((it, *self._put_host_augmented(
                        imgs, labs, epoch, it))):
                    return

        return self._prefetch_iter(fill)

    def _chunk_cap(self) -> int:
        """Batches per staging chunk: WINDOW split into ``host_chunks``
        equal transfers (ceil — the last chunk of a window may be ragged,
        ``_chunk_plan``)."""
        return -(-WINDOW // self.host_chunks)

    def _chunk_plan(self, w: int):
        """The chunk sizes the streaming producer emits for a ``w``-batch
        window: fixed-capacity chunks plus a ragged last.  Shared by the
        producer's flush boundaries and the assembly-program warmup (a
        skewed copy of this arithmetic would warm the wrong arity and pay
        a mid-epoch compile)."""
        cap = self._chunk_cap()
        sizes = [cap] * (w // cap)
        if w % cap:
            sizes.append(w % cap)
        return sizes

    def _probe_put_aliases_host(self, buf: np.ndarray) -> bool:
        """Does ``put_global`` of a committed numpy array on this backend
        ALIAS the host memory instead of copying it?  jax's CPU client
        zero-copies suitably-aligned numpy buffers straight into device
        arrays — under aliasing, rewriting a retired arena row would
        corrupt chunks already handed to the consumer, so the producer puts
        a private copy there instead.  The copy only costs where no real
        host->device link exists; exactly where one does (TPU/GPU), device
        memory is separate, the put must copy, and the arena stays
        zero-copy.  Probed EMPIRICALLY on an actual arena row (aliasing
        depends on backend, sharding layout and buffer alignment, not just
        the backend name)."""
        before = int(buf.flat[0])
        x = meshlib.put_global(buf, self._epoch_sharding)
        jax.block_until_ready(x)
        buf.flat[0] = np.uint8(before ^ 0xFF)
        aliased = int(np.asarray(jax.device_get(x)).flat[0]) != before
        buf.flat[0] = before
        return aliased

    def _chunk_arena(self, cap: int) -> native.StagingArena:
        """The reusable chunk-aligned staging arena (built lazily; rebuilt
        when the chunk shape changes, e.g. a test monkeypatching WINDOW).
        First build also runs the backend aliasing probe that decides
        zero-copy vs copied puts."""
        arena = self._staging_arena
        if arena is not None and arena.chunk_batches == cap:
            return arena
        # Slot budget: the prefetch queue holds up to two windows' worth of
        # transferred chunks (_iter_host_window_chunks' depth) while one
        # more fills; +2 margin so the producer only stalls on a genuinely
        # full pipe, never on arena starvation.
        chunks_per_window = len(self._chunk_plan(WINDOW))
        self._staging_arena = native.StagingArena(
            2 * chunks_per_window + 2, cap, self.global_batch)
        # Probe EVERY slot: aliasing is a per-buffer property (the CPU
        # client's 64-byte alignment criterion — StagingArena docstring),
        # and one aliased slot among non-aliased ones corrupts the stream
        # just as surely, so any aliasing at all flips the path to copies.
        self._staging_put_copies = any(
            self._probe_put_aliases_host(self._staging_arena.buffer(s))
            for s in range(self._staging_arena.nslots))
        return self._staging_arena

    def _iter_host_window_chunks(self, epoch: int):
        """Chunked, double-buffered windowed host-augment pipeline (round
        6).  Round 5 staged each window as ONE blocking whole-window
        ``put_global``: the host->device link idled while the previous
        window computed, and BASELINE.md pinned the path 21% short of its
        target naming exactly this lever.  Here the producer thread fills
        chunk-aligned arena rows via the FUSED C++ gather+augment
        (``native.gather_augment_u8`` — straight from the resident dataset
        into the staging row, collapsing the former gather -> augment ->
        np.stack three-copy chain to one) and ``put_global``s each chunk
        individually, so window w+1's chunk transfers overlap the
        consumer's dispatch of window w; the consumer reassembles the
        device-resident chunks (``_assemble_chunks``) and dispatches the
        scanned window exactly as round 5 did.  Buffers stay UINT8
        (crop/flip host-side, normalize fused into the device step): the
        path's roofline is the host->device link, and uint8 quarters its
        traffic.

        Yields ``("chunk", (k, x[k,B,...]u8, y[k,B]i32, last))`` — ``last``
        marks a window boundary — and ``("tail", (it, x, y))`` for the
        ragged final batch (its own per-step f32 shape, exactly as round
        5).  Batches are augmented with their ABSOLUTE iteration index
        (``_host_aug_params``), so the crop/flip stream is bit-identical to
        the per-step and whole-window paths regardless of ``host_chunks``
        or thread timing — pinned by tests/test_cli_and_profiling.py."""
        cap = self._chunk_cap()
        arena = self._chunk_arena(cap)   # probe runs pre-thread, main thread
        nfull, _ = self._per_rank_batch_counts()
        nlim = nfull if self.limit_train_batches is None \
            else min(nfull, self.limit_train_batches)

        def fill(emit):
            split = self.train_split
            chunk_x = None       # arena row block for the chunk being filled
            slot = -1
            chunk_y: list = []
            filled = 0           # full batches consumed toward windows

            def flush(last: bool) -> bool:
                nonlocal chunk_x, slot
                k = len(chunk_y)
                if k == 0:
                    return True
                with self.telemetry.span("chunk_put", batches=k, last=last):
                    src = chunk_x[:k]
                    if self._staging_put_copies:
                        src = src.copy()
                    x = meshlib.put_global(src, self._epoch_sharding)
                    y = meshlib.put_global(np.asarray(chunk_y, np.int32),
                                           self._epoch_sharding)
                if not self._staging_put_copies:
                    arena.retire(slot, x)
                chunk_x, slot = None, -1
                chunk_y.clear()
                return emit(("chunk", (k, x, y, last)))

            for it, cols in enumerate(_shard_batch_cols(
                    len(split.labels), self.world, self.global_batch,
                    epoch, shuffle=True, seed=self.seed,
                    reshuffle_each_epoch=self.reshuffle_each_epoch)):
                if self.limit_train_batches is not None and \
                        it >= self.limit_train_batches:
                    break
                if len(cols) < self.global_batch:   # ragged tail (last)
                    if not flush(last=True):        # defensive: nlim
                        return                      # boundary flushed it
                    emit(("tail", (it, *self._put_host_augmented(
                        native.gather(split.images, cols),
                        split.labels[cols], epoch, it))))
                    return
                if chunk_x is None:
                    slot, chunk_x = arena.acquire()
                with self.telemetry.span("host_augment"):
                    row = chunk_x[len(chunk_y)]
                    if self.augment:
                        native.gather_augment_u8(
                            split.images, cols,
                            *self._host_aug_params(len(cols), epoch, it),
                            out=row)
                    else:
                        native.gather(split.images, cols, out=row)
                chunk_y.append(split.labels[cols])
                filled += 1
                boundary = filled % WINDOW == 0 or filled == nlim
                if (len(chunk_y) == cap or boundary) and \
                        not flush(last=boundary):
                    return

        # Per-CHUNK queue items: bound the pipe at two windows' worth of
        # chunks — same two-windows-ahead depth round 5's PREFETCH_DEPTH=2
        # gave whole-window items.
        return self._prefetch_iter(
            fill, depth=2 * len(self._chunk_plan(WINDOW)))

    def _per_rank_batch_counts(self):
        """(nfull, tail_per): full per-rank batch count and ragged per-rank
        tail size, from the sampler's ceil wrap-padding — the ONE
        derivation shared by every warmup that must predict the epoch's
        dispatch shapes (a skewed copy yields a mid-epoch compile landing
        inside a timed window)."""
        per = self.global_batch // self.world
        per_rank = -(-len(self.train_split.labels) // self.world)
        return divmod(per_rank, per)

    @staticmethod
    def _window_shape_set(nbatches: int):
        """Distinct scan-window lengths a windowed epoch of ``nbatches``
        full batches dispatches: the full WINDOW plus the ragged last
        group.  Shared by the device and host windowed warmups."""
        shapes = {min(WINDOW, nbatches)} if nbatches else set()
        if nbatches % WINDOW:
            shapes.add(nbatches % WINDOW)
        return shapes

    def _host_window_shapes(self):
        """The window sizes _iter_host_window_chunks will close with a
        ``last`` chunk, computed host-side so compiles can be warmed up
        front."""
        nfull, _ = self._per_rank_batch_counts()
        if self.limit_train_batches is not None:
            nfull = min(nfull, self.limit_train_batches)
        return self._window_shape_set(nfull)

    def _train_model_host_windowed(self, epoch: int) -> WindowedTimers:
        """Windowed host-augment epoch: scanned dispatches over
        chunk-staged C++-augmented buffers (``_iter_host_window_chunks``),
        the reference's print/timing schedule.  The default host-augment
        mode since round 5 — the per-step path remains under
        ``profile_phases`` (where per-batch dispatch is the point)."""
        if self.telemetry.enabled:
            self._emit_collective_telemetry()
        timers = WindowedTimers(self.log, telemetry=self.telemetry,
                                epoch=epoch)
        key = jax.random.fold_in(jax.random.PRNGKey(self.seed), epoch)
        self._warm_per_step_tail_shapes()
        # Warm the window + assembly compiles so none lands inside a timed
        # window.
        for w in self._host_window_shapes():
            cache_key = ("host", w, self.global_batch)
            if cache_key not in self._warmed_window_shapes:
                x_sds = jax.ShapeDtypeStruct(
                    (w, self.global_batch, 32, 32, 3), jnp.uint8,
                    sharding=self._epoch_sharding)
                y_sds = jax.ShapeDtypeStruct(
                    (w, self.global_batch), jnp.int32,
                    sharding=self._epoch_sharding)
                with self.telemetry.span("compile_warmup",
                                         program="train_window_host",
                                         window=w):
                    self.train_window_host.lower(
                        self.state, key, x_sds, y_sds, jnp.int32(0),
                        jnp.zeros((w,), jnp.int8)).compile()
                self._warmed_window_shapes.add(cache_key)
            pattern = tuple(self._chunk_plan(w))
            if len(pattern) > 1:
                akey = ("assemble", pattern, self.global_batch)
                if akey not in self._warmed_window_shapes:
                    def _sds(c, trailing, dtype):
                        return jax.ShapeDtypeStruct(
                            (c, self.global_batch) + trailing, dtype,
                            sharding=self._epoch_sharding)
                    with self.telemetry.span("compile_warmup",
                                             program="assemble_chunks",
                                             chunks=len(pattern)):
                        self._assemble_chunks.lower(
                            *[_sds(c, (32, 32, 3), jnp.uint8)
                              for c in pattern]).compile()
                        self._assemble_chunks.lower(
                            *[_sds(c, (), jnp.int32)
                              for c in pattern]).compile()
                    self._warmed_window_shapes.add(akey)
        chunk_iter = self._iter_host_window_chunks(epoch)
        chunks_x, chunks_y = [], []
        while True:
            # chunk_wait: how long the consumer stalls on the producer —
            # with healthy overlap this is ~0 except at the first window.
            with self.telemetry.span("chunk_wait"):
                item = next(chunk_iter, None)
            if item is None:
                break
            kind, payload = item
            if kind == "tail":   # ragged tail through its own per-step shape
                it, x, y = payload
                t0 = time.time()
                self.state, loss = self.train_step_host(
                    self.state, jax.random.fold_in(key, it), x, y)
                loss = float(loss)  # value fetch = fence
                # steady=False: lone per-dispatch sample carries the fixed
                # dispatch latency the amortized window samples do not.
                timers.record(loss, time.time() - t0, steady=False)
                continue
            k, x, y, last = payload
            chunks_x.append(x)
            chunks_y.append(y)
            if self.telemetry.enabled:
                self.telemetry.gauge("window_chunks_pending", len(chunks_x))
            if not last:
                continue
            # Window boundary: assemble the device-resident chunks and
            # dispatch ONE scanned window, exactly as round 5 (a
            # single-chunk window skips the concatenate — the K=1
            # degenerate case IS round 5's whole-window path).
            if len(chunks_x) == 1:
                xw, yw = chunks_x[0], chunks_y[0]
            else:
                xw = self._assemble_chunks(*chunks_x)
                yw = self._assemble_chunks(*chunks_y)
            chunks_x, chunks_y = [], []
            w = int(xw.shape[0])
            t0 = time.time()
            self.state, losses = self.train_window_host(
                self.state, key, xw, yw, jnp.int32(0),
                jnp.zeros((w,), jnp.int8))
            losses = np.asarray(losses)  # value fetch = fence
            per_iter = (time.time() - t0) / w
            for loss in losses:
                timers.record(float(loss), per_iter)
        self.last_epoch_timers = timers
        return timers

    def _warm_per_step_tail_shapes(self) -> None:
        """AOT-compile the ragged-tail shapes of the per-step programs.

        The full-batch compile lands in the first (warmup) window, which the
        reference's protocol excludes — but the tail arrives at the LAST
        iteration, squarely inside steady state, where a fresh multi-second
        compile would corrupt steady_step_times and the epoch total.  Warm
        both per-step programs at the tail shape up front instead."""
        nfull, tail_per = self._per_rank_batch_counts()
        will_train_tail = tail_per and (self.limit_train_batches is None
                                        or self.limit_train_batches > nfull)
        if not will_train_tail:
            return
        tb = tail_per * self.world
        dtype = np.float32 if self.host_augment else np.uint8
        dtype_name = np.dtype(dtype).name
        x = jax.ShapeDtypeStruct((tb, 32, 32, 3), dtype,
                                 sharding=self._batch_sharding)
        y = jax.ShapeDtypeStruct((tb,), jnp.int32,
                                 sharding=self._batch_sharding)
        key = jax.random.PRNGKey(self.seed)
        step_fn = self.train_step_host if self.host_augment \
            else self.train_step
        if (tb, dtype_name) not in self._warmed_tail_shapes:
            with self.telemetry.span("compile_warmup",
                                     program="per_step_tail", batch=tb):
                step_fn.lower(self.state, key, x, y).compile()
            self._warmed_tail_shapes.add((tb, dtype_name))
        if self.profile_phases and \
                ("fwd", tb, dtype_name) not in self._warmed_tail_shapes:
            with self.telemetry.span("compile_warmup",
                                     program="fwd_only_tail", batch=tb):
                self._fwd_only.lower(
                    self.state.params, self.state.bn_state, x, y).compile()
            self._warmed_tail_shapes.add(("fwd", tb, dtype_name))

    def test_model(self) -> Tuple[float, int, float]:
        """Full-test-set evaluation in one dispatch; prints the reference's
        line (``Part 1/main.py:74-76``): per-batch-averaged CE, correct/total,
        %."""
        with self.telemetry.span("eval"):
            images, labels = self._stage_eval()
            loss_sum, corr = self.eval_window(self.state, images, labels)
            # Value fetches inside the span so it covers real device work.
            loss_sum, corr = float(loss_sum), int(corr)
        n = len(self.test_split.labels)
        if self.limit_eval_batches is not None:
            n = min(n, self.limit_eval_batches * self.global_batch)
        # Reference divides the accumulated per-batch mean losses by the
        # number of batches; we accumulate per-example sums, so divide by n
        # (equal when batches are full; exact even on the ragged tail).
        avg_loss = float(loss_sum) / n
        correct = int(corr)
        acc = 100.0 * correct / n
        self.log("Test set: Average loss: {:.4f}, Accuracy: {}/{} ({:.0f}%)\n"
                 .format(avg_loss, correct, n, acc))
        return avg_loss, correct, acc

    def run(self, epochs: int = 1,
            checkpoint_dir: Optional[str] = None,
            profile_dir: Optional[str] = None) -> None:
        """The reference's run(): epochs of train + eval with epoch timing.

        With ``checkpoint_dir`` set, resumes from the latest saved epoch (if
        any) and persists the full TrainState after every completed epoch —
        beyond-parity (the reference keeps state only in memory); resume is
        bitwise-exact, see train/checkpoint.py.

        With ``profile_dir`` set, the first trained epoch is captured as a
        ``jax.profiler`` trace (XPlane; viewable in TensorBoard/Perfetto) —
        the superset of the reference's print-based timers promised in
        SURVEY.md §5."""
        start_epoch = 0
        mngr = None
        if checkpoint_dir is not None:
            from .checkpoint import CheckpointManager
            # param_tree digests the full state structure (shapes+dtypes),
            # so two "custom" models or any architecture drift fail the
            # guard; real_data catches the silent synthetic-fallback case
            # (same config keys, different dataset).
            param_tree = jax.tree.map(
                lambda a: f"{a.dtype}{list(a.shape)}", self.state)
            mngr = CheckpointManager(checkpoint_dir, config={
                "model": self.model_name, "strategy": self.strategy_name,
                "seed": self.seed, "precision": self.precision,
                "global_batch": self.global_batch, "world": self.world,
                "augment": self.augment,
                "reshuffle_each_epoch": self.reshuffle_each_epoch,
                "lr": self.sgd_cfg.lr, "momentum": self.sgd_cfg.momentum,
                "weight_decay": self.sgd_cfg.weight_decay,
                "limit_train_batches": self.limit_train_batches,
                "real_data": self.real_data,
                "state_digest": str(param_tree)})
            if mngr.latest_epoch() is not None:
                self.state, start_epoch = mngr.restore(self.state)
                self.log(f"Resumed from checkpoint: epoch {start_epoch}")
        try:
            if start_epoch >= epochs:
                self.log(f"All {epochs} epoch(s) already checkpointed; "
                         f"nothing to run"
                         + (" (profile_dir ignored)" if profile_dir else ""))
            for epoch in range(start_epoch, epochs):
                t0 = time.time()
                if profile_dir is not None and epoch == start_epoch:
                    with jax.profiler.trace(profile_dir):
                        self.train_model(epoch)
                else:
                    self.train_model(epoch)
                self.log(f"Training time after {epoch + 1} epoch is "
                         f"{time.time() - t0}")
                if self.telemetry.enabled:
                    self.telemetry.gauge("epoch_time_s", time.time() - t0,
                                         epoch=epoch)
                    self._emit_device_gauges(epoch)
                self.test_model()
                if mngr is not None:
                    with self.telemetry.span("checkpoint_save", epoch=epoch):
                        mngr.save(epoch, self.state)
        finally:
            if mngr is not None:
                mngr.close()

    # -- benchmarking -------------------------------------------------------

    def step_flops_per_image(self, log: Optional[Callable[[str], None]] = None
                             ) -> Optional[float]:
        """FLOPs per trained image, from XLA's cost model of the compiled
        per-batch train step (augment + fwd + bwd + sync + SGD — everything
        the step really runs).  None when the backend offers no cost
        analysis — the reason is logged (``log`` overrides the trainer's
        logger, which bench.py suppresses for the print schedule).
        Used by bench.py for tflops/MFU accounting.

        ``cost_analysis()`` reports the PER-DEVICE SPMD partition, which
        processes global_batch/world images — so the divisor is the
        per-device batch, not the global batch (verified on the 8-virtual-
        device mesh: per-device flops are ~world x smaller than the
        1-device program's for the same global batch)."""
        log = log or self.log
        x = jax.ShapeDtypeStruct((self.global_batch, 32, 32, 3), jnp.uint8,
                                 sharding=self._batch_sharding)
        y = jax.ShapeDtypeStruct((self.global_batch,), jnp.int32,
                                 sharding=self._batch_sharding)
        # Compile errors propagate: this is the same program the trainer
        # runs, so a failure here is a real bug, not a missing cost model.
        comp = self.train_step.lower(
            self.state, jax.random.PRNGKey(0), x, y).compile()
        try:
            ca = comp.cost_analysis()
        except (NotImplementedError, RuntimeError) as e:
            # RuntimeError covers XlaRuntimeError(UNIMPLEMENTED) — the
            # backends-without-cost-analysis case.  Say why MFU is absent
            # instead of silently dropping every MFU field from the bench.
            log(f"MFU accounting unavailable: cost_analysis() failed "
                f"on this backend: {e!r}")
            return None
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        flops = float(ca.get("flops", 0.0)) if ca else 0.0
        if flops <= 0:
            log("MFU accounting unavailable: cost model reported "
                f"flops={flops} for the compiled train step")
            return None
        per_device_batch = self.global_batch // self.world
        return flops / per_device_batch

    def measure_phase_split(self, window_iters: int = 100,
                            windows: int = 3) -> dict:
        """The reference's fwd/bwd phase split
        (``Part 1/main.py:33-43``), window-amortized so it measures the
        chip, not the dispatch path: a forward-only scanned window and the
        full train window are timed alternately over the same staged
        batches, and backward+sync+step ≈ train − forward per iteration.

        The per-step ``profile_phases`` mode keeps the reference's exact
        per-iteration timer placement (and on the tunneled backend
        therefore reports dispatch-dominated times, as its docstring
        warns); THIS is the honest on-chip split.  Each program is timed
        at TWO window sizes (w and w/2), and the per-iteration device cost
        is the SLOPE between them — the per-dispatch fixed cost (~100 ms
        tunnel latency, which differs between the two programs and would
        otherwise contaminate the small forward) cancels exactly.  Each
        total is the best (min) of ``windows`` interleaved timings:
        contention on the shared host is one-sided, so min is the least-
        contaminated estimate (BASELINE.md 'Headline statistic').

        The defaults (W=100, 3 windows) are the configuration of the
        committed BASELINE.md artifact; tools/perf_phase_split.py
        reproduces it.

        The train windows apply REAL optimizer updates while timing (the
        timed program must be the training program); the pre-measurement
        TrainState is snapshotted and restored on return, so measuring
        mid-training does not perturb the trajectory."""
        if self.host_augment:
            raise ValueError(
                "measure_phase_split times the compiled windowed path "
                "(device-side transform); it does not support "
                "host_augment=True — construct a separate Trainer for "
                "the phase split")
        key = jax.random.PRNGKey(self.seed)
        epoch_images, epoch_labels, _ = self._stage_train_epoch(0)
        nbatches = epoch_images.shape[0]
        if nbatches == 0:
            raise ValueError("measure_phase_split needs at least one full "
                             "global batch")
        w = min(window_iters, nbatches)
        half = max(w // 2, 1)
        if w == half:
            raise ValueError("measure_phase_split needs window_iters >= 2 "
                             "for the two-size slope")
        if self._fwd_window is None:   # jit caches are per function object
            self._fwd_window = steplib.make_fwd_window(
                self.apply_fn, self.mesh,
                single=self.strategy_name == "single",
                augment=self.augment, compute_dtype=self.compute_dtype)
        fwd_window = self._fwd_window
        # Deep-copy the state: train_window DONATES its input buffers, so
        # the original arrays are consumed during measurement — the copy is
        # what lets the trajectory be restored afterwards.
        state_snapshot = jax.tree.map(jnp.copy, self.state)
        lengths = {n: jnp.zeros((n,), jnp.int8) for n in (w, half)}
        # Warm both programs at both sizes (compiles excluded from timers).
        for n in (w, half):
            np.asarray(fwd_window(self.state, key, epoch_images,
                                  epoch_labels, jnp.int32(0), lengths[n]))
            self.state, losses = self.train_window(
                self.state, key, epoch_images, epoch_labels, jnp.int32(0),
                lengths[n])
            np.asarray(losses)
        totals = {("fwd", w): [], ("fwd", half): [],
                  ("step", w): [], ("step", half): []}
        for i in range(windows):
            start = jnp.int32((i % max(nbatches // w, 1)) * w)
            for n in (w, half):
                t0 = time.time()
                np.asarray(fwd_window(self.state, key, epoch_images,
                                      epoch_labels, start, lengths[n]))
                totals[("fwd", n)].append(time.time() - t0)
                t0 = time.time()
                self.state, losses = self.train_window(
                    self.state, key, epoch_images, epoch_labels, start,
                    lengths[n])
                np.asarray(losses)  # value fetch = completion fence
                totals[("step", n)].append(time.time() - t0)
        self.state = state_snapshot   # measurement leaves no training trace
        span = w - half
        mins_ms = {f"{prog}_{n}": min(ts) * 1e3
                   for (prog, n), ts in totals.items()}
        fwd_ms = (mins_ms[f"fwd_{w}"] - mins_ms[f"fwd_{half}"]) / span
        step_ms = (mins_ms[f"step_{w}"] - mins_ms[f"step_{half}"]) / span
        return {"window_iters": w, "windows": windows,
                "forward_ms_per_iter": fwd_ms,
                "step_ms_per_iter": step_ms,
                "backward_ms_per_iter": step_ms - fwd_ms,
                "dispatch_ms_fwd_window": mins_ms[f"fwd_{w}"] - fwd_ms * w,
                "dispatch_ms_step_window": (
                    mins_ms[f"step_{w}"] - step_ms * w),
                # Raw min totals (ms) so callers can aggregate mins ACROSS
                # calls — a single contended half-window min makes the
                # within-call slope misleading (even negative); the
                # across-trials slope is the robust estimate
                # (tools/perf_phase_split.py).
                "window_totals_ms": mins_ms}

    def steady_state_throughput(self, max_iters: int = 3 * WINDOW,
                                window_iters=None) -> Tuple[float, float]:
        """(images/sec, images/sec/chip) over steady-state iterations,
        using the reference's measurement design: windowed dispatches, the
        first window (compile+warmup) excluded.

        ``window_iters`` sets the iterations per compiled dispatch:
        ``"epoch"`` = the whole epoch per dispatch (what bench.py uses on
        TPU), an int = that many, None = min(epoch, max(max_iters, WINDOW)).
        Windows LARGER than the reference's 20-iteration reporting window
        are deliberate: each dispatch through the tunneled TPU backend
        costs ~100 ms of host-side latency regardless of size (measured;
        tools/perf_pieces.py), which at 20-iter windows would measure the
        tunnel, not the chip (~51k vs ~88k img/s at the headline config).
        The reference-parity path (train_model) keeps the 20-iteration
        granularity for its print schedule; documented in BASELINE.md."""
        if self.host_augment:
            raise ValueError(
                "steady_state_throughput measures the compiled windowed "
                "path (device-side transform); it does not support "
                "host_augment=True — construct a separate Trainer for "
                "throughput measurement")
        key = jax.random.PRNGKey(self.seed)
        epoch_images, epoch_labels, _ = self._stage_train_epoch(0)
        nbatches = epoch_images.shape[0]
        if nbatches == 0:
            raise ValueError(
                "steady_state_throughput needs at least one full global "
                f"batch ({self.global_batch}); the dataset holds only a "
                "ragged tail")
        if window_iters == "epoch":
            w = nbatches
        else:
            w = min(window_iters or max(max_iters, WINDOW), nbatches)
        length_arr = jnp.zeros((w,), jnp.int8)
        nwin = max(2, -(-max_iters // w))
        starts = [i * w for i in range(max(nbatches // w, 1))] or [0]

        # Per-window keys, FOLDED AHEAD OF the timed region: when the start
        # offsets wrap around a small epoch, the same batches get fresh
        # augmentation randomness instead of replaying the previous pass's
        # stream — but a host-side fold_in between dispatches would break
        # the back-to-back window chain with a tiny interleaved program
        # (~6% throughput on v5e), so all keys are materialized up front.
        keys = [jax.device_put(k) for k in
                jax.random.split(key, nwin + 1)]
        for k in keys:
            np.asarray(k)  # value fetch: keep transfers out of timed region

        def dispatch(start, wi):
            self.state, losses = self.train_window(
                self.state, keys[wi], epoch_images,
                epoch_labels, jnp.int32(start), length_arr)
            return losses

        # Window 0: compile + warmup (excluded, as the reference excludes its
        # first 20-iteration window).  Fetching the losses is the fence.
        _ = np.asarray(dispatch(0, 0))
        # Steady state: windows dispatch back-to-back — the state pytree
        # chains every step sequentially on device — and all losses are
        # fetched after the last window, which transitively fences the whole
        # chain.  (train_model, the reference-parity path, syncs per window
        # to print; the bench measures device throughput.)
        t0 = time.time()
        pending = []
        for i in range(nwin):
            pending.append(dispatch(starts[(1 + i) % len(starts)], 1 + i))
        for losses in pending:
            _ = np.asarray(losses)
        elapsed = time.time() - t0
        ips = self.global_batch * w * nwin / elapsed
        return ips, ips / self.world
