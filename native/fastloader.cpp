// fastloader — native host-side data pipeline kernels.
//
// The reference delegates its host data path to native library code:
// torchvision's C transforms plus torch DataLoader worker processes
// (reference: /root/reference/src/Part 1/main.py:82-109, num_workers=2).
// This library supplies the TPU build's equivalent: multithreaded batch
// gather and augmentation (pad-4 random crop + horizontal flip + channel
// normalization) over NHWC uint8 CIFAR images, exposed as a C API consumed
// via ctypes (cs744_ddp_tpu/data/native.py).
//
// Build: make -C native   (g++ -O3 -shared -fPIC, no external deps)

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <thread>
#include <vector>

namespace {

constexpr int kH = 32, kW = 32, kC = 3, kPad = 4;
constexpr int kImg = kH * kW * kC;

inline void worker_range(int n, int nthreads, int t, int* lo, int* hi) {
  int chunk = (n + nthreads - 1) / nthreads;
  *lo = t * chunk;
  *hi = std::min(n, *lo + chunk);
}

template <typename F>
void parallel_for_images(int n, int nthreads, F&& fn) {
  nthreads = std::max(1, std::min(nthreads, n));
  if (nthreads == 1) {
    fn(0, n);
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(nthreads);
  for (int t = 0; t < nthreads; ++t) {
    int lo, hi;
    worker_range(n, nthreads, t, &lo, &hi);
    if (lo >= hi) break;
    threads.emplace_back([&fn, lo, hi] { fn(lo, hi); });
  }
  for (auto& th : threads) th.join();
}

}  // namespace

extern "C" {

// Gather rows of a [num_images, 32*32*3] uint8 dataset into a batch:
// out[i] = dataset[indices[i]].  The numpy equivalent (fancy indexing)
// is single-threaded; this spreads the memcpy over threads.
void fl_gather_u8(const uint8_t* dataset, const int64_t* indices, int n,
                  uint8_t* out, int nthreads) {
  parallel_for_images(n, nthreads, [&](int lo, int hi) {
    for (int i = lo; i < hi; ++i) {
      std::memcpy(out + (size_t)i * kImg,
                  dataset + (size_t)indices[i] * kImg, kImg);
    }
  });
}

// Pad-4 random crop + optional horizontal flip + normalize to float32.
// images: [n,32,32,3] uint8; offsets: [n,2] int32 in [0,8]; flips: [n] u8;
// mean/std: [3] float32 applied after x/255.  out: [n,32,32,3] float32.
// Zero padding semantics match torchvision's RandomCrop(32, padding=4)
// (reference main.py:85).
void fl_augment_f32(const uint8_t* images, int n, const int32_t* offsets,
                    const uint8_t* flips, const float* mean, const float* std_,
                    float* out, int nthreads) {
  float scale[kC], bias[kC];
  for (int c = 0; c < kC; ++c) {
    scale[c] = 1.0f / (255.0f * std_[c]);
    bias[c] = -mean[c] / std_[c];
  }
  parallel_for_images(n, nthreads, [&](int lo, int hi) {
    for (int i = lo; i < hi; ++i) {
      const uint8_t* img = images + (size_t)i * kImg;
      float* dst = out + (size_t)i * kImg;
      const int oy = offsets[2 * i], ox = offsets[2 * i + 1];
      const bool flip = flips[i] != 0;
      for (int y = 0; y < kH; ++y) {
        const int sy = y + oy - kPad;  // source row in the unpadded image
        for (int x = 0; x < kW; ++x) {
          const int xx = flip ? (kW - 1 - x) : x;
          const int sx = xx + ox - kPad;
          float* px = dst + ((size_t)y * kW + x) * kC;
          if (sy < 0 || sy >= kH || sx < 0 || sx >= kW) {
            for (int c = 0; c < kC; ++c) px[c] = bias[c];  // zero-pixel
          } else {
            const uint8_t* sp = img + ((size_t)sy * kW + sx) * kC;
            for (int c = 0; c < kC; ++c)
              px[c] = (float)sp[c] * scale[c] + bias[c];
          }
        }
      }
    }
  });
}

// Pad-4 random crop + optional horizontal flip, staying uint8 (zero
// padding).  The transfer-compact variant of fl_augment_f32 for windowed
// staging: the stochastic transform happens here on the host; the affine
// normalize (a per-channel scale+bias the compiler fuses into the first
// conv's input read) runs on device, so the wire carries 1 byte/px, not 4.
void fl_augment_u8(const uint8_t* images, int n, const int32_t* offsets,
                   const uint8_t* flips, uint8_t* out, int nthreads) {
  parallel_for_images(n, nthreads, [&](int lo, int hi) {
    for (int i = lo; i < hi; ++i) {
      const uint8_t* img = images + (size_t)i * kImg;
      uint8_t* dst = out + (size_t)i * kImg;
      const int oy = offsets[2 * i], ox = offsets[2 * i + 1];
      const bool flip = flips[i] != 0;
      for (int y = 0; y < kH; ++y) {
        const int sy = y + oy - kPad;
        if (sy < 0 || sy >= kH) {
          std::memset(dst + (size_t)y * kW * kC, 0, kW * kC);
          continue;
        }
        for (int x = 0; x < kW; ++x) {
          const int xx = flip ? (kW - 1 - x) : x;
          const int sx = xx + ox - kPad;
          uint8_t* px = dst + ((size_t)y * kW + x) * kC;
          if (sx < 0 || sx >= kW) {
            px[0] = px[1] = px[2] = 0;
          } else {
            const uint8_t* sp = img + ((size_t)sy * kW + sx) * kC;
            px[0] = sp[0]; px[1] = sp[1]; px[2] = sp[2];
          }
        }
      }
    }
  });
}

// Fused gather + pad-4 crop + optional flip, uint8 in/out: one pass from
// the resident dataset straight into a caller-provided staging slot
// (cs744_ddp_tpu/data/native.py StagingArena).  The windowed host-augment
// path previously ran gather (copy 1) -> fl_augment_u8 into a fresh batch
// (copy 2) -> np.stack into the window buffer (copy 3) before the
// host->device put; this entry point collapses all three host copies into
// one, with `out` pointing directly at the chunk-aligned arena row.
void fl_gather_augment_u8(const uint8_t* dataset, const int64_t* indices,
                          int n, const int32_t* offsets, const uint8_t* flips,
                          uint8_t* out, int nthreads) {
  parallel_for_images(n, nthreads, [&](int lo, int hi) {
    for (int i = lo; i < hi; ++i) {
      const uint8_t* img = dataset + (size_t)indices[i] * kImg;
      uint8_t* dst = out + (size_t)i * kImg;
      const int oy = offsets[2 * i], ox = offsets[2 * i + 1];
      const bool flip = flips[i] != 0;
      for (int y = 0; y < kH; ++y) {
        const int sy = y + oy - kPad;
        if (sy < 0 || sy >= kH) {
          std::memset(dst + (size_t)y * kW * kC, 0, kW * kC);
          continue;
        }
        for (int x = 0; x < kW; ++x) {
          const int xx = flip ? (kW - 1 - x) : x;
          const int sx = xx + ox - kPad;
          uint8_t* px = dst + ((size_t)y * kW + x) * kC;
          if (sx < 0 || sx >= kW) {
            px[0] = px[1] = px[2] = 0;
          } else {
            const uint8_t* sp = img + ((size_t)sy * kW + sx) * kC;
            px[0] = sp[0]; px[1] = sp[1]; px[2] = sp[2];
          }
        }
      }
    }
  });
}

// Normalize only (the test transform: ToTensor + Normalize, main.py:91-93).
void fl_normalize_f32(const uint8_t* images, int n, const float* mean,
                      const float* std_, float* out, int nthreads) {
  float scale[kC], bias[kC];
  for (int c = 0; c < kC; ++c) {
    scale[c] = 1.0f / (255.0f * std_[c]);
    bias[c] = -mean[c] / std_[c];
  }
  parallel_for_images(n, nthreads, [&](int lo, int hi) {
    const size_t lo_px = (size_t)lo * kH * kW, hi_px = (size_t)hi * kH * kW;
    for (size_t p = lo_px; p < hi_px; ++p) {
      for (int c = 0; c < kC; ++c)
        out[p * kC + c] = (float)images[p * kC + c] * scale[c] + bias[c];
    }
  });
}

int fl_version() { return 3; }  // 2: + fl_augment_u8; 3: + fl_gather_augment_u8

}  // extern "C"
