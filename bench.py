"""Benchmark: steady-state CIFAR-10 training throughput (images/sec/chip).

Prints ONE JSON line whose head matches the driver contract
({"metric", "value", "unit", "vs_baseline"}) and which additionally carries

  * ``matrix``  — per-(strategy x model) images/sec/chip over all available
    chips, the reference's strategy-cost spectrum
    (``/root/reference/src/Part 2a/main.py:83-112`` vs ``Part 2b`` vs
    ``Part 3`` — its entire pedagogical point), and
  * ``scaling`` — a 1..N-device sweep with efficiency vs the 1-device run
    (the BASELINE.json north star: >=90% efficiency 1->8 chips).  On a
    1-chip host the sweep is degenerate ({"1": ...}, efficiency 1.0); the
    harness itself is exercised on the 8-virtual-device CPU mesh in
    tests/test_bench.py.

Protocol (BASELINE.md): the reference's own measurement design — per-step
wall-clock fenced by fetching the loss values, 20-iteration windows, the first
window (compile + warmup) excluded — global batch 256, SGD(0.1, 0.9, 1e-4).

vs_baseline: the reference publishes no numbers (BASELINE.json
"published": {}), so the comparison point is the reference's own stack
measured on this host — torch CPU VGG-11 fwd+bwd+step at batch 256
(tools/bench_torch_baseline.py: 38.9 images/sec; see BASELINE.md).
"""

import argparse
import json
import os
import sys

# Reference stack on this host (torch CPU, batch 256): images/sec.
# Measured with tools/bench_torch_baseline.py (38.9 img/s); see BASELINE.md.
TORCH_CPU_BASELINE_IPS = 38.9

MODELS = ("vgg11", "resnet18")
STRATEGIES = ("gather", "allreduce", "ddp")


def _throughput(model: str, strategy: str, num_devices, *, global_batch: int,
                max_iters: int, data_dir: str, log,
                precision: str = "f32") -> float:
    """images/sec/chip for one configuration (fresh Trainer + mesh)."""
    from cs744_ddp_tpu.train.loop import Trainer

    trainer = Trainer(model=model, strategy=strategy,
                      num_devices=num_devices, global_batch=global_batch,
                      data_dir=data_dir, precision=precision, log=log)
    _, ips_per_chip = trainer.steady_state_throughput(max_iters=max_iters)
    return ips_per_chip


def run_bench(*, matrix: bool = True, sweep: bool = True,
              peak: bool = True, max_iters: int = 100,
              global_batch: int = 256,
              models=MODELS, strategies=STRATEGIES,
              headline_model: str = "vgg11", peak_batch_per_chip: int = 2048,
              log=None) -> dict:
    import jax

    log = log or (lambda s: print(s, file=sys.stderr))
    data_dir = os.environ.get("CIFAR_DATA_DIR", "./data")
    ndev = len(jax.devices())

    # Headline: the flagship config on all chips (ddp when the mesh is
    # non-trivial; Part-1 'single' semantics on one chip).  Best of two
    # independent runs — the standard convention for throughput under
    # ONE-SIDED noise (timeit reports min latency for the same reason):
    # the bench host is shared, so slow runs are contaminated by external
    # contention while the fastest run is the least-contaminated estimate
    # of device capability; identical code measured ±10% across
    # invocations here.  Each run excludes its own compile+warmup window
    # per the reference's protocol.  Documented in BASELINE.md.
    headline_strategy = "ddp" if ndev > 1 else "single"
    log(f"[bench] headline: {headline_model}/{headline_strategy} "
        f"on {ndev} device(s), best of 2")
    headline_runs = [
        _throughput(headline_model, headline_strategy, ndev,
                    global_batch=global_batch, max_iters=max_iters,
                    data_dir=data_dir, log=lambda s: None)
        for _ in range(2)]
    headline = max(headline_runs)

    result = {
        "metric": f"cifar10_{headline_model}_images_per_sec_per_chip",
        "value": round(headline, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(headline / TORCH_CPU_BASELINE_IPS, 2),
        "num_devices": ndev,
    }

    if matrix:
        result["matrix"] = {}
        for model in models:
            for strategy in strategies:
                if model == headline_model and strategy == headline_strategy:
                    # Iteration-for-iteration identical to a headline run —
                    # reuse a single run instead of a third measurement.
                    result["matrix"][f"{model}/{strategy}"] = round(
                        headline_runs[0], 2)
                    continue
                log(f"[bench] matrix: {model}/{strategy} on {ndev} device(s)")
                ips = _throughput(model, strategy, ndev,
                                  global_batch=global_batch,
                                  max_iters=max_iters, data_dir=data_dir,
                                  log=lambda s: None)
                result["matrix"][f"{model}/{strategy}"] = round(ips, 2)

    # Peak throughput: the parity protocol pins global batch 256 / f32
    # (the reference's config), which underfills the MXU on one chip; this
    # reports the frontier with both constraints lifted (bf16 mixed
    # precision, 2048 images PER CHIP) — same measurement design.
    if peak:
        peak_global = peak_batch_per_chip * ndev
        log(f"[bench] peak: {headline_model}/bf16/batch{peak_global} "
            f"on {ndev} device(s)")
        ips = _throughput(headline_model, headline_strategy, ndev,
                          global_batch=peak_global,
                          max_iters=max(max_iters // 3, 2),
                          data_dir=data_dir, log=lambda s: None,
                          precision="bf16")
        result["peak"] = {
            "config": f"{headline_model}/bf16/global_batch={peak_global}",
            "images_per_sec_per_chip": round(ips, 2),
        }

    if sweep:
        counts = [n for n in (1, 2, 4, 8, 16, 32, 64) if n <= ndev]
        if counts[-1] != ndev:
            counts.append(ndev)
        per_chip = {}
        for n in counts:
            strat_n = "ddp" if n > 1 else "single"
            # The all-chip point duplicates a config already measured (the
            # matrix's ddp entry on multi-chip hosts; one of the headline's
            # runs on a 1-chip host): reuse a SINGLE-run value instead of
            # restaging + recompiling the identical config.  Never the
            # best-of-2 headline itself — every sweep point must carry the
            # same (single-run) statistic or efficiency ratios are biased.
            cached = result.get("matrix", {}).get(f"{headline_model}/{strat_n}")
            if n == ndev and cached is None and strat_n == headline_strategy:
                cached = headline_runs[0]
            if n == ndev and cached is not None:
                per_chip[n] = cached
                continue
            log(f"[bench] sweep: {headline_model}/{strat_n} on {n} device(s)")
            per_chip[n] = _throughput(headline_model, strat_n, n,
                                      global_batch=global_batch,
                                      max_iters=max_iters, data_dir=data_dir,
                                      log=lambda s: None)
        base = per_chip[1]
        result["scaling"] = {
            "images_per_sec_per_chip": {str(n): round(v, 2)
                                        for n, v in per_chip.items()},
            "efficiency_vs_1chip": {str(n): round(v / base, 3)
                                    for n, v in per_chip.items()},
        }
    return result


def _enable_compilation_cache() -> None:
    """Persist XLA compilations (the matrix compiles six train-window
    programs, ~40 s each on TPU, identical across bench invocations)."""
    from cs744_ddp_tpu.utils.compcache import \
        enable_persistent_compilation_cache
    enable_persistent_compilation_cache(
        os.path.dirname(os.path.abspath(__file__)))


def main(argv=None) -> None:
    p = argparse.ArgumentParser("bench")
    p.add_argument("--no-matrix", action="store_true",
                   help="headline metric only (fast driver mode; also "
                        "skips the peak entry)")
    p.add_argument("--no-sweep", action="store_true",
                   help="skip the 1..N-device scaling sweep")
    p.add_argument("--no-peak", action="store_true",
                   help="skip the bf16 large-batch peak-throughput entry")
    p.add_argument("--max-iters", type=int, default=100,
                   help="steady-state iterations per matrix/sweep config")
    p.add_argument("--global-batch", type=int, default=256)
    args = p.parse_args(argv)

    _enable_compilation_cache()
    result = run_bench(matrix=not args.no_matrix, sweep=not args.no_sweep,
                       peak=not (args.no_peak or args.no_matrix),
                       max_iters=args.max_iters,
                       global_batch=args.global_batch)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
