"""Benchmark: steady-state CIFAR-10 training throughput (images/sec/chip).

Prints ONE JSON line whose head matches the driver contract
({"metric", "value", "unit", "vs_baseline"}) and which additionally carries

  * ``headline_stats`` — all N=3 independent headline runs with best /
    median / min (noise robustness on a shared host whose contention is
    one-sided; the BEST run is the least-contaminated estimate of device
    capability, the same rationale as ``timeit``'s min-latency convention —
    median and min are reported alongside so the spread is visible).
    Every per-config measurement (headline runs, matrix, peak, sweep) is
    itself best-of-2 on one staged trainer, so a single contaminated
    window cannot land in the output verbatim and all entries carry the
    same statistic,
  * ``matrix``  — per-(strategy x model) throughput over all available
    chips, the reference's strategy-cost spectrum
    (``/root/reference/src/Part 2a/main.py:83-112`` vs ``Part 2b`` vs
    ``Part 3`` — its entire pedagogical point), each entry with
    ``tflops_per_sec`` and ``mfu_vs_bf16_peak`` derived from XLA's cost
    model of the compiled step (197 TFLOP/s bf16 peak per v5e chip), and
  * ``scaling`` — a 1..N-device WEAK-scaling sweep (per-chip batch held
    constant) with efficiency vs the 1-device run (the BASELINE.json north
    star: >=90% images/sec/chip efficiency 1->8 chips) and per-point MFU,
    plus a ``strong`` sub-section measuring the reference's own protocol
    (global batch 256 divided across workers).  On a 1-chip host the sweep
    is degenerate ({"1": ...}, efficiency 1.0); the harness itself is
    exercised on the 8-virtual-device CPU mesh in tests/test_bench.py,
  * ``convergence`` — the reference's correctness oracle (test accuracy,
    ``Part 1/main.py:74-76``) as a per-epoch TRAJECTORY over 3 epochs at
    the reference config, plus a ``stable_lr`` companion entry (1 epoch
    at lr 0.01 — the reference lr collapses big models on the synthetic
    stand-in; see BASELINE.md), labeled ``real_data`` false when the
    synthetic fallback is in use (this host has no egress), and
  * ``spectrum`` — static per-strategy collective counts, comm bytes and
    dependency-chain depths from the TPU v5e-8 AOT lowering (the strategy
    tiers' cost AND latency shapes, independent of wall-clock noise), and
  * ``host_pipeline`` — windowed ``--host-augment`` throughput (the
    reference's DataLoader-worker model; host->device-link-bound on the
    tunneled bench host, see BASELINE.md).

Protocol (BASELINE.md): the reference's own measurement design — windowed
wall-clock fenced by fetching the loss values, the first window (compile +
warmup) excluded — global batch 256, SGD(0.1, 0.9, 1e-4).  Bench windows
are EPOCH-LENGTH (one compiled dispatch per pass over the data): the
tunneled TPU backend charges ~100 ms host latency per dispatch, which at
the reference's 20-iteration granularity would measure the tunnel, not the
chip (tools/perf_pieces.py).  The parity path (Trainer.train_model) keeps
the reference's 20-iteration reporting.

vs_baseline: the reference publishes no numbers (BASELINE.json
"published": {}), so the comparison point is the reference's own stack
measured on this host — torch CPU VGG-11 fwd+bwd+step at batch 256
(tools/bench_torch_baseline.py: 38.9 images/sec; see BASELINE.md).
"""

import argparse
import json
import os
import statistics
import sys

# Reference stack on this host (torch CPU, batch 256): images/sec.
# Measured with tools/bench_torch_baseline.py (38.9 img/s); see BASELINE.md.
TORCH_CPU_BASELINE_IPS = 38.9

# TPU v5e: 197 TFLOP/s bf16 peak per chip (the MFU denominator; f32 configs
# use the same denominator since TPU f32 matmuls run bf16 multiply passes).
V5E_BF16_PEAK_FLOPS = 197e12

MODELS = ("vgg11", "resnet18")
STRATEGIES = ("gather", "allreduce", "ddp")
# Deep-model rows measured in the matrix beyond the full strategy cross:
# the deep end of both families, ddp only (at world=1 the strategy spread
# is near-zero information — BASELINE.md "1-chip strategy matrix" — but
# depth-scaling regressions like the per-family BN fence choice show up
# exactly here; VERDICT r4 item 7).
DEEP_ROWS = (("vgg19", "ddp"), ("resnet34", "ddp"))
HEADLINE_RUNS = 3


def _make_trainer(model: str, strategy: str, num_devices, *,
                  global_batch: int, data_dir: str, log,
                  precision: str = "f32", sgd_cfg=None, **extra):
    """Central Trainer construction; ``extra`` passes through any further
    Trainer kwargs (host_augment, limit_train_batches, ...)."""
    from cs744_ddp_tpu.train.loop import Trainer
    if sgd_cfg is not None:
        extra["sgd_cfg"] = sgd_cfg
    return Trainer(model=model, strategy=strategy, num_devices=num_devices,
                   global_batch=global_batch, data_dir=data_dir,
                   precision=precision, log=log, **extra)


def _throughput(model: str, strategy: str, num_devices, *, global_batch: int,
                max_iters: int, data_dir: str, log,
                precision: str = "f32", want_flops: bool = False,
                repeats: int = 1, flops_log=None):
    """(images/sec/chip, flops_per_image | None) for one configuration.

    ``repeats`` > 1 re-measures on the SAME staged/compiled trainer and
    keeps the best — host contention is one-sided, and a single
    contaminated measurement otherwise lands in the output verbatim (a
    round-3 trial's matrix entry read 30% low this way).

    ``flops_log`` receives the MFU-unavailable reason (the trainer's own
    ``log`` is suppressed in bench runs to mute the print schedule)."""
    trainer = _make_trainer(model, strategy, num_devices,
                            global_batch=global_batch, data_dir=data_dir,
                            precision=precision, log=log)
    # Epoch-length windows: one compiled dispatch per pass over the data
    # (see steady_state_throughput's docstring re dispatch latency).
    ips_per_chip = max(
        trainer.steady_state_throughput(
            max_iters=max_iters, window_iters="epoch")[1]
        for _ in range(max(repeats, 1)))
    flops = trainer.step_flops_per_image(log=flops_log) if want_flops else None
    return ips_per_chip, flops


def _mfu_fields(ips_per_chip: float, flops_per_image) -> dict:
    """tflops_per_sec / mfu_vs_bf16_peak for one chip's throughput."""
    if not flops_per_image:
        return {}
    tflops = ips_per_chip * flops_per_image / 1e12
    return {"tflops_per_sec": round(tflops, 2),
            "mfu_vs_bf16_peak": round(tflops * 1e12 / V5E_BF16_PEAK_FLOPS, 4)}


def _collect_spectrum(log, model: str, global_batch: int):
    """Static per-strategy collective stats from the TPU v5e-8 AOT lowering
    (deviceless topology — compiles anywhere the TPU compiler is present).

    This is the strategy-cost spectrum as the COMPILER sees it: collective
    instruction counts and result-buffer bytes per tier, immune to host
    noise.  None (with a logged reason) where the TPU AOT client is
    unavailable."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from cs744_ddp_tpu import models as model_zoo
    from cs744_ddp_tpu.ops import sgd as sgdlib
    from cs744_ddp_tpu.parallel import get_strategy
    from cs744_ddp_tpu.parallel.mesh import DATA_AXIS
    from cs744_ddp_tpu.train import step as steplib
    from cs744_ddp_tpu.utils.hlo_stats import (collective_chain_depth,
                                               collective_stats)

    try:
        from jax.experimental import topologies
        topo = topologies.get_topology_desc("v5e:2x4", platform="tpu")
    except Exception as e:
        log(f"[bench] spectrum: TPU AOT topology unavailable ({e!r}); "
            "section omitted")
        return None
    # The lowering shards the batch 8 ways regardless of how many devices
    # the measurement host has; keep it divisible.
    global_batch = -(-global_batch // 8) * 8
    mesh = Mesh(np.array(topo.devices), (DATA_AXIS,))
    init_fn, apply_fn = model_zoo.get_model(model)
    state = steplib.init_train_state(init_fn, jax.random.PRNGKey(0))
    rep = NamedSharding(mesh, P())
    sh = NamedSharding(mesh, P(DATA_AXIS))
    state_sds = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=rep), state)
    args = (state_sds,
            jax.ShapeDtypeStruct((2,), jnp.uint32, sharding=rep),
            jax.ShapeDtypeStruct((global_batch, 32, 32, 3), jnp.uint8,
                                 sharding=sh),
            jax.ShapeDtypeStruct((global_batch,), jnp.int32, sharding=sh))
    grad_bytes = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                     for a in jax.tree.leaves(state.params))
    out = {
        "topology": "v5e:2x4 (AOT, deviceless)",
        "model": model, "global_batch": global_batch,
        "grad_mib": round(grad_bytes / 2**20, 2),
        "note": "result_mib sums collective RESULT buffers: all-gather's "
                "is world x its input, so the gather tier's world-times "
                "traffic amplification (vs the reference's root-link "
                "gather, Part 2a/main.py:117-127) is explicit — see "
                "BASELINE.md 'Gather-tier traffic accounting'",
        "per_strategy": {},
    }
    for name in ("gather", "allreduce", "ddp"):
        log(f"[bench] spectrum: AOT-compiling {model}/{name} for v5e-8")
        try:
            step = steplib.make_train_step(
                apply_fn, get_strategy(name), mesh, sgdlib.SGDConfig(),
                augment=True)
            low = step.lower(*args)
            # Latency shape: collectives forced sequential by data deps in
            # the pre-optimization HLO (barrier chains still visible there;
            # see hlo_stats.collective_chain_depth) — gather 2/leaf chained,
            # allreduce 1/leaf chained, ddp 1/bucket independent.
            chain_depth = collective_chain_depth(
                low.compiler_ir(dialect="hlo").as_hlo_text())
            txt = low.compile().as_text()
        except Exception as e:
            # Never let the static section kill a bench whose expensive
            # measurements already completed — omit it with the reason.
            log(f"[bench] spectrum: AOT compile failed for {name} "
                f"({e!r}); section omitted")
            return None
        stats = collective_stats(txt)
        if stats["total_count"] == 0:
            # Every tier in this loop MUST lower to collectives on an 8-chip
            # mesh; zero means the HLO-text parser no longer matches this
            # XLA version's print format — omit the section rather than
            # record misleading zeros.
            log(f"[bench] spectrum: parsed 0 collectives for {name} on the "
                "8-chip lowering — HLO text format mismatch; section omitted")
            return None
        stats["chain_depth"] = chain_depth
        out["per_strategy"][name] = stats
    return out


def run_bench(*, matrix: bool = True, sweep: bool = True,
              peak: bool = True, convergence: bool = True,
              convergence_epochs: int = 3,
              spectrum: bool = True, host_pipeline: bool = True,
              max_iters: int = 100,
              global_batch: int = 256,
              models=MODELS, strategies=STRATEGIES, deep_rows=DEEP_ROWS,
              headline_model: str = "vgg11",
              peak_batch_candidates=(1536, 2048),
              log=None) -> dict:
    import jax

    log = log or (lambda s: print(s, file=sys.stderr))
    data_dir = os.environ.get("CIFAR_DATA_DIR", "./data")
    ndev = len(jax.devices())

    # Headline: the flagship config on all chips (ddp when the mesh is
    # non-trivial; Part-1 'single' semantics on one chip), best of
    # HEADLINE_RUNS independent runs with median/min recorded — see module
    # docstring and BASELINE.md for the one-sided-noise rationale.
    headline_strategy = "ddp" if ndev > 1 else "single"
    log(f"[bench] headline: {headline_model}/{headline_strategy} "
        f"on {ndev} device(s), best of {HEADLINE_RUNS}")
    headline_runs = []
    headline_flops = None
    for _ in range(HEADLINE_RUNS):
        ips, fl = _throughput(headline_model, headline_strategy, ndev,
                              global_batch=global_batch, max_iters=max_iters,
                              data_dir=data_dir, log=lambda s: None,
                              want_flops=headline_flops is None, repeats=2,
                              flops_log=log)
        headline_runs.append(ips)
        headline_flops = headline_flops or fl
    headline = max(headline_runs)

    result = {
        "metric": f"cifar10_{headline_model}_images_per_sec_per_chip",
        "value": round(headline, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(headline / TORCH_CPU_BASELINE_IPS, 2),
        "num_devices": ndev,
        "headline_stats": {
            "runs": [round(r, 2) for r in headline_runs],
            "best": round(max(headline_runs), 2),
            "median": round(statistics.median(headline_runs), 2),
            "min": round(min(headline_runs), 2),
        },
        **_mfu_fields(headline, headline_flops),
    }

    # Convergence oracle — the reference's own correctness signal (test
    # accuracy after training, /root/reference/src/Part 1/main.py:74-76),
    # tracked per round so the artifact carries it, not just a test
    # assertion — and as a TRAJECTORY (per-epoch accuracy over
    # ``convergence_epochs``; a half-broken step can luck into one
    # above-chance epoch, not a rising multi-epoch trend — VERDICT r4
    # item 3).  On this egress-less bench host the dataset is the
    # deterministic synthetic fallback (real_data=false, labels derived
    # from image statistics — learnable, so accuracy moves well above the
    # 10% chance floor); real-CIFAR accuracy remains unverifiable here
    # (BASELINE.md).
    if convergence:
        log(f"[bench] convergence: {headline_model}/{headline_strategy}, "
            f"{convergence_epochs} epochs @ reference config")
        # In-memory telemetry recorder (no out_dir): the section's steady-
        # state step-time percentiles ride along in the bench artifact.
        from cs744_ddp_tpu.obs import Telemetry
        conv_tel = Telemetry()
        trainer = _make_trainer(headline_model, headline_strategy, ndev,
                                global_batch=global_batch, data_dir=data_dir,
                                log=lambda s: None, telemetry=conv_tel)
        per_epoch = []
        first_loss = None
        for ep in range(convergence_epochs):
            timers = trainer.train_model(ep)
            if first_loss is None:
                first_loss = timers.losses[0]
            avg_loss, _, acc = trainer.test_model()
            per_epoch.append({
                "train_loss_last": round(timers.losses[-1], 4),
                "test_avg_loss": round(avg_loss, 4),
                "test_accuracy_pct": round(acc, 2),
            })
        result["convergence"] = {
            "protocol": f"{convergence_epochs} epochs, reference config "
                        f"(global batch {global_batch}, SGD 0.1/0.9/1e-4, "
                        "f32)",
            "train_loss_first": round(first_loss, 4),
            "train_loss_last": per_epoch[-1]["train_loss_last"],
            "test_avg_loss": per_epoch[-1]["test_avg_loss"],
            "test_accuracy_pct": per_epoch[-1]["test_accuracy_pct"],
            "per_epoch": per_epoch,
            "real_data": trainer.real_data,
            "telemetry_summary": conv_tel.finalize(
                global_batch=global_batch),
        }
        # Companion entry at a stable lr: the reference's lr=0.1 is tuned
        # for real CIFAR-10 and COLLAPSES the big models on the synthetic
        # stand-in (VGG-11 probe: accuracy frozen at exactly 19.7% for 8
        # epochs, loss asymptote ~2.0 — a degenerate minimum, measured
        # round 5), which would read as a broken trainer.  lr=0.01 shows
        # the framework's actual convergence behavior on the same data
        # (VGG-11: 100% test accuracy after ONE epoch).
        from cs744_ddp_tpu.ops import sgd as _sgd
        stable_cfg = _sgd.SGDConfig(lr=0.01)
        log(f"[bench] convergence: {headline_model}/{headline_strategy}, "
            f"1 epoch @ stable lr {stable_cfg.lr}")
        tr2 = _make_trainer(headline_model, headline_strategy, ndev,
                            global_batch=global_batch, data_dir=data_dir,
                            log=lambda s: None, sgd_cfg=stable_cfg)
        timers2 = tr2.train_model(0)
        avg_loss2, _, acc2 = tr2.test_model()
        result["convergence"]["stable_lr"] = {
            "protocol": f"1 epoch, SGD {stable_cfg.lr}/"
                        f"{stable_cfg.momentum}/"
                        f"{stable_cfg.weight_decay}, f32",
            "train_loss_last": round(timers2.losses[-1], 4),
            "test_avg_loss": round(avg_loss2, 4),
            "test_accuracy_pct": round(acc2, 2),
        }

    if spectrum:
        spec = _collect_spectrum(log, headline_model, global_batch)
        if spec is not None:
            result["spectrum"] = spec

    if matrix:
        result["matrix"] = {}
        # flops depend on (model, precision, batch) only — strategies share.
        model_flops = {headline_model: headline_flops}
        pairs = [(m, s) for m in models for s in strategies]
        pairs += [tuple(r) for r in deep_rows if tuple(r) not in pairs]
        for model, strategy in pairs:
            entry_key = f"{model}/{strategy}"
            if model == headline_model and strategy == headline_strategy:
                # Iteration-for-iteration identical to a headline run —
                # reuse one run instead of another measurement.
                ips = headline_runs[0]
            else:
                log(f"[bench] matrix: {entry_key} on {ndev} device(s)")
                ips, fl = _throughput(
                    model, strategy, ndev, global_batch=global_batch,
                    max_iters=max_iters, data_dir=data_dir,
                    log=lambda s: None,
                    want_flops=model not in model_flops, repeats=2,
                    flops_log=log)
                model_flops.setdefault(model, fl)
            result["matrix"][entry_key] = {
                "images_per_sec_per_chip": round(ips, 2),
                **_mfu_fields(ips, model_flops.get(model)),
            }

    # Peak throughput: the parity protocol pins global batch 256 / f32
    # (the reference's config), which underfills the MXU on one chip; this
    # reports the frontier with both constraints lifted (bf16 mixed
    # precision, large per-chip batch) — same measurement design.  The
    # frontier is a SEARCH over the two best measured batch candidates
    # (1536 then 2048 images/chip; the day-long sweep measured
    # 1536 > 2048 > 2560 > 3072 on v5e, within a couple % of each other),
    # reporting the winning config — which also shields the headline peak
    # from a single moment of host contention.
    if peak:
        best, best_ips = None, None
        for per_chip_batch in dict.fromkeys(peak_batch_candidates):
            peak_global = per_chip_batch * ndev
            log(f"[bench] peak: {headline_model}/bf16/batch{peak_global} "
                f"on {ndev} device(s)")
            ips, fl = _throughput(
                headline_model, headline_strategy, ndev,
                global_batch=peak_global, max_iters=max(max_iters // 3, 2),
                data_dir=data_dir, log=lambda s: None,
                precision="bf16", want_flops=True, repeats=2,
                flops_log=log)
            # Compare UNROUNDED ips (the stored value is rounded; a
            # near-tie within the rounding step could otherwise pick a
            # candidate inconsistent with the reported numbers).
            if best_ips is None or ips > best_ips:
                best_ips = ips
                best = {
                    "config": f"{headline_model}/bf16/"
                              f"global_batch={peak_global}",
                    "images_per_sec_per_chip": round(ips, 2),
                    **_mfu_fields(ips, fl),
                }
        result["peak"] = best

    # Host-pipeline throughput: the --host-augment mode (the reference's
    # DataLoader-worker model — C++ crop/flip on host, windowed uint8
    # staging since round 5).  Regression-tracked here because its wins
    # were previously hand-measured only (BASELINE.md: 1,235 serial ->
    # 1,756 prefetched -> 13,805 windowed img/s on the tunneled v5e
    # host); bounded by the host->device link, not the chip.
    if host_pipeline:
        log(f"[bench] host_pipeline: {headline_model}/{headline_strategy}/"
            "--host-augment, windowed")
        # Cap at 98 batches (~half an epoch at batch 256): the path is
        # host->device-link-bound at ~15 ms/batch on the tunneled host
        # (BASELINE.md), so a full --max-iters run would spend minutes
        # measuring the wire for no extra information.
        lim = min(max_iters, 98)
        if lim < max_iters:
            log(f"[bench] host_pipeline: capped at {lim} batches "
                f"(link-bound path; --max-iters {max_iters} applies to "
                "the device-bound sections)")
        from cs744_ddp_tpu.obs import Telemetry as _Telemetry
        host_tel = _Telemetry()   # in-memory; summary attached below
        trh = _make_trainer(headline_model, headline_strategy, ndev,
                            global_batch=global_batch, data_dir=data_dir,
                            log=lambda s: None, host_augment=True,
                            limit_train_batches=lim, telemetry=host_tel)
        # Images actually trained per epoch: the limit may exceed the
        # epoch's full-batch count (large global batches), in which case
        # the ragged tail trains too — assuming lim batches would inflate
        # the rate.
        nfull, tail_per = trh._per_rank_batch_counts()
        images = (min(lim, nfull) * global_batch
                  + (tail_per * trh.world
                     if lim > nfull and tail_per else 0))
        import time as _time
        trh.train_model(0)  # compile + warm
        best_ips = 0.0
        for _ in range(3):
            t0 = _time.time()
            trh.train_model(0)
            best_ips = max(best_ips, images / (_time.time() - t0))
        from cs744_ddp_tpu.data import native as _native
        result["host_pipeline"] = {
            "mode": "windowed uint8 staging (fl_augment_u8), "
                    "normalize fused on device",
            # False = the C++ library failed to load and the NumPy
            # fallback ran — a much slower number that must not be read
            # as a regression of the native path.
            "native_lib": _native.available(),
            "images_per_sec_per_chip": round(best_ips / ndev, 2),
            # Spans cover host_augment / prefetch_put wall clock; the
            # percentiles cover the timed epochs' steady windows.
            "telemetry_summary": host_tel.finalize(
                global_batch=global_batch),
        }

    if sweep:
        # WEAK scaling: per-chip batch held at ``global_batch`` while the
        # mesh grows (global = global_batch x n).  The north star is
        # images/sec/CHIP efficiency (BASELINE.json >=90% at 1->8), which
        # is a constant-per-chip-work metric: at the reference's fixed
        # global 256 on 8 chips the per-chip batch would be 32 against a
        # full 37 MB gradient all-reduce per step — comm-dominated by
        # construction, measuring the protocol rather than the framework.
        # The reference's own strong-scaling config (global 256 divided
        # across workers) is what the MATRIX measures.
        counts = [n for n in (1, 2, 4, 8, 16, 32, 64) if n <= ndev]
        if counts[-1] != ndev:
            counts.append(ndev)
        per_chip, sweep_flops = {}, {}
        for n in counts:
            strat_n = "ddp" if n > 1 else "single"
            # n=1 with per-chip batch == global_batch is exactly a headline
            # run's config on a 1-chip host: reuse one run's value (same
            # best-of-2-per-trainer statistic as fresh sweep points).
            if n == 1 and ndev == 1 and strat_n == headline_strategy:
                per_chip[n] = headline_runs[0]
                sweep_flops[n] = headline_flops
                continue
            log(f"[bench] sweep: {headline_model}/{strat_n} on {n} "
                f"device(s), global batch {global_batch * n}")
            per_chip[n], sweep_flops[n] = _throughput(
                headline_model, strat_n, n, global_batch=global_batch * n,
                max_iters=max_iters, data_dir=data_dir, log=lambda s: None,
                repeats=2, want_flops=True, flops_log=log)
        base = per_chip[1]
        result["scaling"] = {
            "protocol": f"weak scaling, {global_batch} images/chip",
            "images_per_sec_per_chip": {str(n): round(v, 2)
                                        for n, v in per_chip.items()},
            "efficiency_vs_1chip": {str(n): round(v / base, 3)
                                    for n, v in per_chip.items()},
            "mfu_vs_bf16_peak": {
                str(n): _mfu_fields(v, sweep_flops[n]).get("mfu_vs_bf16_peak")
                for n, v in per_chip.items()},
        }

        # STRONG scaling — the reference's own protocol (global batch 256
        # DIVIDED across workers, Part 2a/main.py:22): the per-chip batch
        # shrinks as the mesh grows, so comm exposure rises by construction
        # (BASELINE.md "Scaling protocol").  Reported alongside the weak
        # sweep so both protocols are on the record; efficiency is
        # global-throughput(n) / (n x global-throughput(1)), which reduces
        # to the same per-chip ratio as the weak formula.
        strong_counts = [n for n in counts if global_batch % n == 0]
        strong = {}
        for n in strong_counts:
            strat_n = "ddp" if n > 1 else "single"
            if n == 1 and 1 in per_chip:
                strong[n] = per_chip[1]   # identical config: reuse
                continue
            log(f"[bench] sweep(strong): {headline_model}/{strat_n} on {n} "
                f"device(s), global batch {global_batch}")
            strong[n], _ = _throughput(
                headline_model, strat_n, n, global_batch=global_batch,
                max_iters=max_iters, data_dir=data_dir, log=lambda s: None,
                repeats=2)
        result["scaling"]["strong"] = {
            "protocol": f"strong scaling, global batch {global_batch} "
                        "(the reference's config)",
            "images_per_sec": {str(n): round(v * n, 2)
                               for n, v in strong.items()},
            "efficiency_vs_1chip": {str(n): round(v / strong[1], 3)
                                    for n, v in strong.items()},
        }
    return result


def _enable_compilation_cache() -> None:
    """Persist XLA compilations (the matrix compiles six train-window
    programs, ~40 s each on TPU, identical across bench invocations)."""
    from cs744_ddp_tpu.utils.compcache import \
        enable_persistent_compilation_cache
    enable_persistent_compilation_cache(
        os.path.dirname(os.path.abspath(__file__)))


def main(argv=None) -> None:
    p = argparse.ArgumentParser("bench")
    p.add_argument("--no-matrix", action="store_true",
                   help="headline metric only (fast driver mode; also "
                        "skips the peak, convergence and spectrum "
                        "sections)")
    p.add_argument("--no-sweep", action="store_true",
                   help="skip the 1..N-device scaling sweep")
    p.add_argument("--no-peak", action="store_true",
                   help="skip the bf16 large-batch peak-throughput entry")
    p.add_argument("--no-convergence", action="store_true",
                   help="skip the 1-epoch accuracy (convergence oracle) "
                        "entry")
    p.add_argument("--no-spectrum", action="store_true",
                   help="skip the static per-strategy collective-stats "
                        "section (v5e-8 AOT lowering)")
    p.add_argument("--no-host-pipeline", action="store_true",
                   help="skip the windowed --host-augment throughput entry")
    p.add_argument("--max-iters", type=int, default=100,
                   help="minimum steady-state iterations per config")
    p.add_argument("--global-batch", type=int, default=256)
    args = p.parse_args(argv)

    _enable_compilation_cache()
    result = run_bench(matrix=not args.no_matrix, sweep=not args.no_sweep,
                       peak=not (args.no_peak or args.no_matrix),
                       convergence=not (args.no_convergence
                                        or args.no_matrix),
                       spectrum=not (args.no_spectrum or args.no_matrix),
                       host_pipeline=not (args.no_host_pipeline
                                          or args.no_matrix),
                       max_iters=args.max_iters,
                       global_batch=args.global_batch)
    payload = json.dumps(result)
    # Self-validate before emitting: the driver parses this single line, so
    # a non-serializable value (numpy scalar, NaN under a strict parser)
    # must fail HERE with a clear error, not downstream in the consumer.
    reparsed = json.loads(payload)
    if reparsed.keys() != result.keys():
        raise RuntimeError("bench JSON round-trip dropped keys: "
                           f"{set(result) ^ set(reparsed)}")
    print(payload)


if __name__ == "__main__":
    main()
