"""Benchmark: steady-state CIFAR-10 training throughput (images/sec/chip).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Protocol (BASELINE.md): the reference's own measurement design — per-step
wall-clock fenced with block_until_ready, 20-iteration windows, the first
window (compile + warmup) excluded — on the flagship config: VGG-11,
CIFAR-10 (synthetic stand-in when the real set is absent; identical shapes
and dtypes), global batch 256, SGD(0.1, 0.9, 1e-4), bucketed-fused 'ddp'
strategy over all available chips.

vs_baseline: the reference publishes no numbers (BASELINE.json
"published": {}), so the comparison point is the reference's own stack
measured on this host — torch CPU VGG-11 fwd+bwd+step at batch 256
(see BASELINE.md "host torch CPU baseline"; measured at 38.9 images/sec
on this machine).
"""

import json
import os
import sys

# Reference stack on this host (torch CPU, batch 256): images/sec.
# Measured with tools/bench_torch_baseline.py (38.9 img/s); see BASELINE.md.
TORCH_CPU_BASELINE_IPS = 38.9


def main() -> None:
    # Use whatever platform the driver provides (TPU under axon; CPU in CI).
    import jax

    from cs744_ddp_tpu.train.loop import Trainer

    ndev = len(jax.devices())
    strategy = "ddp" if ndev > 1 else "single"
    trainer = Trainer(model="vgg11", strategy=strategy,
                      num_devices=ndev, global_batch=256,
                      data_dir=os.environ.get("CIFAR_DATA_DIR", "./data"),
                      log=lambda s: print(s, file=sys.stderr))
    ips, ips_per_chip = trainer.steady_state_throughput(max_iters=200)
    print(json.dumps({
        "metric": "cifar10_vgg11_images_per_sec_per_chip",
        "value": round(ips_per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(ips_per_chip / TORCH_CPU_BASELINE_IPS, 2),
    }))


if __name__ == "__main__":
    main()
