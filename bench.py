"""Benchmark: steady-state CIFAR-10 training throughput (images/sec/chip).

Emission contract (VERDICT r5 item 1): the FINAL stdout line is a COMPACT
JSON head ({"metric", "value", "unit", "vs_baseline", "headline_stats",
MFU fields}) guaranteed to fit the driver's 2000-byte tail capture — the
full result grew past that bound in rounds 4/5 and the driver recorded
``parsed: null``.  The full payload is printed as an EARLIER stdout line
and written to a sidecar file (``BENCH_FULL.json``, committed) named by
the head's ``full_payload_file`` field; ``emit_result`` implements and
tests pin both.  The full payload carries

  * ``headline_stats`` — all N=3 independent headline runs with best /
    median / min (noise robustness on a shared host whose contention is
    one-sided; the BEST run is the least-contaminated estimate of device
    capability, the same rationale as ``timeit``'s min-latency convention —
    median and min are reported alongside so the spread is visible).
    Every per-config measurement (headline runs, matrix, peak, sweep) is
    itself best-of-2 on one staged trainer, so a single contaminated
    window cannot land in the output verbatim and all entries carry the
    same statistic,
  * ``matrix``  — per-(strategy x model) throughput over all available
    chips, the reference's strategy-cost spectrum
    (``/root/reference/src/Part 2a/main.py:83-112`` vs ``Part 2b`` vs
    ``Part 3`` — its entire pedagogical point), each entry with
    ``tflops_per_sec`` and ``mfu_vs_bf16_peak`` derived from XLA's cost
    model of the compiled step (197 TFLOP/s bf16 peak per v5e chip), and
  * ``scaling`` — a 1..N-device WEAK-scaling sweep (per-chip batch held
    constant) with efficiency vs the 1-device run (the BASELINE.json north
    star: >=90% images/sec/chip efficiency 1->8 chips) and per-point MFU,
    plus a ``strong`` sub-section measuring the reference's own protocol
    (global batch 256 divided across workers).  On a 1-chip host the sweep
    is degenerate ({"1": ...}, efficiency 1.0); the harness itself is
    exercised on the 8-virtual-device CPU mesh in tests/test_bench.py,
  * ``convergence`` — the reference's correctness oracle (test accuracy,
    ``Part 1/main.py:74-76``) as a per-epoch TRAJECTORY over 3 epochs at
    the reference config, plus a ``stable_lr`` companion entry (1 epoch
    at lr 0.01 — a faster-learning control the CI floor rides on; see
    BASELINE.md "Synthetic-task recalibration (round 7)" for the graded
    trajectory the stand-in now shows), labeled ``real_data`` false when the
    synthetic fallback is in use (this host has no egress), and
  * ``spectrum`` — static per-strategy collective counts, comm bytes and
    dependency-chain depths from the TPU v5e-8 AOT lowering (the strategy
    tiers' cost AND latency shapes, independent of wall-clock noise), and
  * ``compression`` — the round-7 gradient-compression cost sheet
    (``run_compression``): per-tier MEASURED collective result bytes
    from the pre-optimization lowering (with the ratio vs the
    uncompressed per-param tier), interleaved min-over-rounds epoch
    wall clock, and the convergence delta vs the uncompressed tier
    after an identical training schedule, and
  * ``host_pipeline`` — chunked windowed ``--host-augment`` throughput
    (the reference's DataLoader-worker model; host->device-link-bound on
    the tunneled bench host, see BASELINE.md), alongside the measured
    pure-``device_put`` LINK FLOOR on synthetic and real-entropy bytes
    (``measure_link_floor``) so the path's target is a fraction of
    measured hardware rather than a round number, plus a ``chunk_sweep``
    over the staging chunk count K, and
  * ``robustness`` — the fault-tolerance layer's cost/benefit sheet
    (``run_robustness``): non-finite-guard throughput overhead, the
    degraded synchronous staging fallback as a fraction of the healthy
    chunked pipeline, emergency mid-epoch checkpoint save/restore wall
    clock with the steps-lost accounting, and a deterministic
    chaos-injected NaN-skip demo, and
  * ``serving`` — the inference fast path (``run_serving``,
    ``cs744_ddp_tpu/serve/``): throughput-vs-bucket curve over the AOT
    executable ladder (per-dispatch fenced latency AND the amortized
    device-program time — on the tunneled TPU host the two differ by the
    ~100 ms dispatch tax, see BASELINE.md), client-side latency
    p50/p95/p99 under a seeded open-loop arrival trace at 2-3 offered
    loads, and COLD vs WARM startup seconds measured in fresh
    subprocesses sharing one executable-cache dir (the warm-start
    acceptance bar: warm < 0.5 x cold), and
  * ``pipeline`` — the round-14 dispatch-pipeline cost sheet
    (``run_pipeline``): per-rung serial vs pipelined steady-state
    per-dispatch time vs the device-program floor (``gap_closed``),
    capacity goodput with the scheduler pipeline on vs off over the same
    seeded traces, and the pipelined capacity point's stage waterfall
    (staging / device-compute / fetch) with the two-slot occupancy
    distribution and the per-bucket measured-over-cost-prior ratio, and
  * ``attribution`` — the round-8 performance-attribution sheet
    (``run_attribution``): the static cost model
    (``analysis/costmodel.py``) over every zoo program's lowering
    (analytic FLOPs/HBM/wire bytes -> roofline bound, MFU ceiling,
    comm/compute ratio; overlap's exposed-comm bound vs ddp's chained
    plan) plus a measured MFU join of the headline windowed program's
    steady-state wall clock against its own audited lowering.

Protocol (BASELINE.md): the reference's own measurement design — windowed
wall-clock fenced by fetching the loss values, the first window (compile +
warmup) excluded — global batch 256, SGD(0.1, 0.9, 1e-4).  Bench windows
are EPOCH-LENGTH (one compiled dispatch per pass over the data): the
tunneled TPU backend charges ~100 ms host latency per dispatch, which at
the reference's 20-iteration granularity would measure the tunnel, not the
chip (tools/perf_pieces.py).  The parity path (Trainer.train_model) keeps
the reference's 20-iteration reporting.

vs_baseline: the reference publishes no numbers (BASELINE.json
"published": {}), so the comparison point is the reference's own stack
measured on this host — torch CPU VGG-11 fwd+bwd+step at batch 256
(tools/bench_torch_baseline.py: 38.9 images/sec; see BASELINE.md).
"""

import argparse
import json
import os
import statistics
import sys
from typing import Optional

# Reference stack on this host (torch CPU, batch 256): images/sec.
# Measured with tools/bench_torch_baseline.py (38.9 img/s); see BASELINE.md.
TORCH_CPU_BASELINE_IPS = 38.9

# TPU v5e: 197 TFLOP/s bf16 peak per chip (the MFU denominator; f32 configs
# use the same denominator since TPU f32 matmuls run bf16 multiply passes).
# Single source: analysis/costmodel.py (jax-free), shared with the MFU and
# roofline tooling so the constant cannot drift between reports.
from cs744_ddp_tpu.analysis.costmodel import (  # noqa: E402
    V5E_BF16_PEAK_FLOPS, mfu_fields as _costmodel_mfu_fields)

MODELS = ("vgg11", "resnet18")
STRATEGIES = ("gather", "allreduce", "ddp")
# Deep-model rows measured in the matrix beyond the full strategy cross:
# the deep end of both families, ddp only (at world=1 the strategy spread
# is near-zero information — BASELINE.md "1-chip strategy matrix" — but
# depth-scaling regressions like the per-family BN fence choice show up
# exactly here; VERDICT r4 item 7).
DEEP_ROWS = (("vgg19", "ddp"), ("resnet34", "ddp"))
HEADLINE_RUNS = 3


def _make_trainer(model: str, strategy: str, num_devices, *,
                  global_batch: int, data_dir: str, log,
                  precision: str = "f32", sgd_cfg=None, **extra):
    """Central Trainer construction; ``extra`` passes through any further
    Trainer kwargs (host_augment, limit_train_batches, ...)."""
    from cs744_ddp_tpu.train.loop import Trainer
    if sgd_cfg is not None:
        extra["sgd_cfg"] = sgd_cfg
    return Trainer(model=model, strategy=strategy, num_devices=num_devices,
                   global_batch=global_batch, data_dir=data_dir,
                   precision=precision, log=log, **extra)


def _throughput(model: str, strategy: str, num_devices, *, global_batch: int,
                max_iters: int, data_dir: str, log,
                precision: str = "f32", want_flops: bool = False,
                repeats: int = 1, flops_log=None):
    """(images/sec/chip, flops_per_image | None) for one configuration.

    ``repeats`` > 1 re-measures on the SAME staged/compiled trainer and
    keeps the best — host contention is one-sided, and a single
    contaminated measurement otherwise lands in the output verbatim (a
    round-3 trial's matrix entry read 30% low this way).

    ``flops_log`` receives the MFU-unavailable reason (the trainer's own
    ``log`` is suppressed in bench runs to mute the print schedule)."""
    trainer = _make_trainer(model, strategy, num_devices,
                            global_batch=global_batch, data_dir=data_dir,
                            precision=precision, log=log)
    # Epoch-length windows: one compiled dispatch per pass over the data
    # (see steady_state_throughput's docstring re dispatch latency).
    ips_per_chip = max(
        trainer.steady_state_throughput(
            max_iters=max_iters, window_iters="epoch")[1]
        for _ in range(max(repeats, 1)))
    flops = trainer.step_flops_per_image(log=flops_log) if want_flops else None
    return ips_per_chip, flops


def _mfu_fields(ips_per_chip: float, flops_per_image) -> dict:
    """tflops_per_sec / mfu_vs_bf16_peak for one chip's throughput
    (delegates to analysis/costmodel.mfu_fields — the one copy of the
    arithmetic and rounding)."""
    return _costmodel_mfu_fields(ips_per_chip, flops_per_image)


def _matrix_pairs(ndev: int, models, strategies, deep_rows):
    """The (model, strategy) rows the matrix measures.

    At world=1 every strategy's sync collapses to a no-op, so the full
    strategy cross is near-duplicate rows for zero information
    (BASELINE.md "1-chip strategy matrix": spread within noise) — prune to
    ONE strategy per model ("ddp", the flagship, or the first offered) and
    reinvest the minutes in the bf16 deep row run_bench adds.  Deep rows
    append beyond the cross either way."""
    if ndev > 1:
        pairs = [(m, s) for m in models for s in strategies]
    else:
        keep = "ddp" if "ddp" in strategies else strategies[0]
        pairs = [(m, keep) for m in models]
    pairs += [tuple(r) for r in deep_rows if tuple(r) not in pairs]
    return pairs


def measure_link_floor(log, *, global_batch: int, ndev: int,
                       trials: int = 5) -> dict:
    """Pure host->device goodput floor for the chunked staging path: time
    nothing but ``put_global`` of WINDOW-sized uint8 buffers (the exact
    shape/sharding the producer ships) and convert to an images/sec/chip
    CEILING for the host pipeline.  Two byte distributions, because the
    tunneled TPU transport compresses:

      * ``synthetic`` — the class-templated synthetic split this
        egress-less bench host actually trains on (compressible; round 5
        measured the achieved pipeline ABOVE the incompressible-bytes
        wire rate for exactly this reason), and
      * ``real_entropy`` — real CIFAR-10 images from the committed
        tests/assets fixture, tiled to fill the window (``unique_mib``
        records how little unique content backs the tiling — an upper
        bound on how compressible-in-principle the buffer is).

    The host_pipeline target derived from this is "achieved >= X% of the
    matching measured floor" (BASELINE.md, VERDICT item 3 closure) —
    regression-tracked against hardware, not a round number."""
    import time as _time

    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from cs744_ddp_tpu.data import cifar10
    from cs744_ddp_tpu.parallel import mesh as meshlib
    from cs744_ddp_tpu.utils.metrics import WINDOW

    mesh = meshlib.make_mesh(None)
    sharding = NamedSharding(mesh, P(None, meshlib.DATA_AXIS))
    shape = (WINDOW, global_batch, 32, 32, 3)
    per_image = 32 * 32 * 3
    buf_mib = WINDOW * global_batch * per_image / 2**20

    def _fill_tiled(images: np.ndarray) -> np.ndarray:
        flat = images.reshape(-1, 32, 32, 3)
        reps = -(-WINDOW * global_batch // len(flat))
        tiled = np.tile(flat, (reps, 1, 1, 1))[:WINDOW * global_batch]
        return np.ascontiguousarray(tiled.reshape(shape))

    def _measure(buf: np.ndarray) -> dict:
        # Two alternating source buffers so no put can be served from a
        # same-object cache; they diverge by a per-trial byte flip.  Both
        # are copies: buf may alias the memoized (read-only) split.
        bufs = [buf.copy(), buf.copy()]
        best = float("inf")
        for t in range(trials + 1):   # +1 warmup (first put pays setup)
            src = bufs[t % 2]
            src[0, 0, 0, 0, 0] ^= 0xFF   # defeat content-level caching
            t0 = _time.time()
            x = meshlib.put_global(src, sharding)
            x.block_until_ready()
            # Value fetch of one element: under the tunneled backend
            # block_until_ready can return before the transfer completes.
            np.asarray(x[0, 0, 0, 0, 0])
            dt = _time.time() - t0
            del x
            if t > 0:
                best = min(best, dt)
        images_per_s = WINDOW * global_batch / best
        return {
            "mib_per_s": round(buf_mib / best, 1),
            "ms_per_batch": round(best / WINDOW * 1e3, 2),
            "floor_images_per_sec_per_chip": round(images_per_s / ndev, 1),
        }

    log(f"[bench] link_floor: {WINDOW}x{global_batch} u8 window "
        f"({buf_mib:.1f} MiB), best of {trials}")
    synth = cifar10._synthetic_split(WINDOW * global_batch, seed=7)
    out = {
        # In-process CPU "transfers" are memcpys (or aliased no-ops) —
        # only a tpu backend's floor is a statement about the wire.
        "backend": jax.default_backend(),
        "window_batches": WINDOW,
        "buffer_mib": round(buf_mib, 2),
        "trials": trials,
        "synthetic": _measure(np.ascontiguousarray(
            synth.images.reshape(shape))),
    }
    fixture_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "tests", "assets")
    if cifar10.has_real_data(fixture_dir):
        real, _, _ = cifar10.load(fixture_dir)[:3]
        entry = _measure(_fill_tiled(real.images))
        entry["unique_mib"] = round(
            real.images.size / 2**20, 2)
        out["real_entropy"] = entry
    else:   # fixture missing on this checkout: floor still has one leg
        log("[bench] link_floor: tests/assets CIFAR fixture missing; "
            "real-entropy leg omitted")
        out["real_entropy"] = None
    return out


def _collect_spectrum(log, model: str, global_batch: int,
                      strategies=STRATEGIES,
                      deep_rows=(("resnet34", "allreduce"),
                                 ("resnet34", "ddp"))):
    """Static per-strategy collective stats from the TPU v5e-8 AOT lowering
    (deviceless topology — compiles anywhere the TPU compiler is present).

    This is the strategy-cost spectrum as the COMPILER sees it: collective
    instruction counts and result-buffer bytes per tier, immune to host
    noise.  ``per_strategy`` covers the headline ``model`` across
    ``strategies``; ``deep_rows`` adds (model, strategy) rows for a deep
    model (many more parameter leaves -> the chained-collective tiers'
    latency shape scales with depth, where the bucketed ddp tier's does
    not — that contrast IS the row's information).  None (with a logged
    reason) where the TPU AOT client is unavailable."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from cs744_ddp_tpu import models as model_zoo
    from cs744_ddp_tpu.ops import sgd as sgdlib
    from cs744_ddp_tpu.parallel import get_strategy
    from cs744_ddp_tpu.parallel.mesh import DATA_AXIS
    from cs744_ddp_tpu.train import step as steplib
    from cs744_ddp_tpu.analysis import (collective_chain_depth,
                                        collective_stats)

    try:
        from jax.experimental import topologies
        topo = topologies.get_topology_desc("v5e:2x4", platform="tpu")
    except Exception as e:
        log(f"[bench] spectrum: TPU AOT topology unavailable ({e!r}); "
            "section omitted")
        return None
    # The lowering shards the batch 8 ways regardless of how many devices
    # the measurement host has; keep it divisible.
    global_batch = -(-global_batch // 8) * 8
    mesh = Mesh(np.array(topo.devices), (DATA_AXIS,))
    rep = NamedSharding(mesh, P())
    sh = NamedSharding(mesh, P(DATA_AXIS))
    model_cache = {}

    def _model_args(name):
        """(apply_fn, step args, grad bytes) for one model, cached — the
        deep rows reuse the headline model's init where they share it."""
        if name not in model_cache:
            init_fn, apply_fn = model_zoo.get_model(name)
            state = steplib.init_train_state(init_fn, jax.random.PRNGKey(0))
            state_sds = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                               sharding=rep), state)
            args = (state_sds,
                    jax.ShapeDtypeStruct((2,), jnp.uint32, sharding=rep),
                    jax.ShapeDtypeStruct((global_batch, 32, 32, 3),
                                         jnp.uint8, sharding=sh),
                    jax.ShapeDtypeStruct((global_batch,), jnp.int32,
                                         sharding=sh))
            grad_bytes = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                             for a in jax.tree.leaves(state.params))
            model_cache[name] = (apply_fn, args, grad_bytes)
        return model_cache[name]

    def _strategy_stats(mname, sname):
        """collective_stats + chain_depth for one (model, strategy), or
        None with the reason logged."""
        apply_fn, args, _ = _model_args(mname)
        log(f"[bench] spectrum: AOT-compiling {mname}/{sname} for v5e-8")
        try:
            step = steplib.make_train_step(
                apply_fn, get_strategy(sname), mesh, sgdlib.SGDConfig(),
                augment=True)
            low = step.lower(*args)
            # Latency shape: collectives forced sequential by data deps in
            # the pre-optimization HLO (barrier chains still visible there;
            # see hlo_stats.collective_chain_depth) — gather 2/leaf chained,
            # allreduce 1/leaf chained, ddp 1/bucket independent.
            chain_depth = collective_chain_depth(
                low.compiler_ir(dialect="hlo").as_hlo_text())
            txt = low.compile().as_text()
        except Exception as e:
            # Never let the static section kill a bench whose expensive
            # measurements already completed — omit it with the reason.
            log(f"[bench] spectrum: AOT compile failed for {mname}/{sname} "
                f"({e!r}); section omitted")
            return None
        stats = collective_stats(txt)
        if stats["total_count"] == 0:
            # Every tier here MUST lower to collectives on an 8-chip
            # mesh; zero means the HLO-text parser no longer matches this
            # XLA version's print format — omit the section rather than
            # record misleading zeros.
            log(f"[bench] spectrum: parsed 0 collectives for "
                f"{mname}/{sname} on the 8-chip lowering — HLO text "
                "format mismatch; section omitted")
            return None
        stats["chain_depth"] = chain_depth
        return stats

    _, _, grad_bytes = _model_args(model)
    out = {
        "topology": "v5e:2x4 (AOT, deviceless)",
        "model": model, "global_batch": global_batch,
        "grad_mib": round(grad_bytes / 2**20, 2),
        "note": "result_mib sums collective RESULT buffers: all-gather's "
                "is world x its input, so the gather tier's world-times "
                "traffic amplification (vs the reference's root-link "
                "gather, Part 2a/main.py:117-127) is explicit — see "
                "BASELINE.md 'Gather-tier traffic accounting'",
        "per_strategy": {},
    }
    for name in strategies:
        stats = _strategy_stats(model, name)
        if stats is None:
            return None
        out["per_strategy"][name] = stats
    if deep_rows:
        out["deep_rows"] = {}
        for mname, sname in deep_rows:
            stats = _strategy_stats(mname, sname)
            if stats is None:
                return None
            _, _, gb = _model_args(mname)
            stats["grad_mib"] = round(gb / 2**20, 2)
            out["deep_rows"][f"{mname}/{sname}"] = stats
    return out


def run_robustness(log, *, headline_model: str = "vgg11",
                   headline_strategy=None, ndev=None,
                   global_batch: int = 256, data_dir: str = "./data",
                   max_iters: int = 100) -> dict:
    """Fault-tolerance cost/benefit numbers for the ft/ layer, measured:

    * ``guard_overhead`` — steady-state throughput with the non-finite
      step guard compiled in (``nonfinite="skip"``) vs the unguarded
      program.  The guard adds an on-device finiteness check of loss +
      global grad sqnorm and a per-leaf select to every step; this is the
      price of never applying a poisoned update.
    * ``staging`` — the degraded synchronous staging fallback (what a
      doubly-failed producer leaves you with) vs the healthy chunked
      pipeline, on the ``--host-augment`` path.  The fallback ships the
      bit-identical batch stream (tests/test_ft.py pins it), so this ratio
      is the whole cost of losing the producer thread.
    * ``checkpoint`` — emergency mid-epoch save + restore wall clock (what
      a SIGTERM costs on the way down and the way back up), plus the
      steps-lost accounting: step-level checkpoints replay 0 steps,
      epoch-only checkpointing replays everything since the last epoch
      boundary (worst case one full epoch).
    * ``nonfinite_skip`` — end-to-end demo: a deterministically injected
      NaN gradient (chaos ``nonfinite_grad``) under the skip policy;
      records the skip count and that the run finishes finite.

    Standalone-callable (the committed artifact's robustness section can be
    refreshed without re-running the day-long throughput sections)."""
    import tempfile
    import time as _time

    import jax
    import numpy as np

    from cs744_ddp_tpu.ft import ChaosPlan, FTConfig
    from cs744_ddp_tpu.utils.metrics import WINDOW

    log = log or (lambda s: print(s, file=sys.stderr))
    ndev = ndev or len(jax.devices())
    headline_strategy = headline_strategy or ("ddp" if ndev > 1 else "single")
    out = {
        "backend": jax.default_backend(),
        "model": f"{headline_model}/{headline_strategy}",
        "global_batch": global_batch,
    }

    # Guard overhead: same measurement design as the matrix (epoch-length
    # windows, best-of-2 on one staged trainer).  NOTE the guarded program
    # is a DIFFERENT compiled program (the check + select change XLA's
    # fusion), so the comparison is throughput-vs-throughput, not
    # bitwise-vs.
    # Bounded epoch: the guard ratio stabilizes within a couple of windows,
    # so the "epoch" each dispatch covers is capped at max_iters batches —
    # still one dispatch per pass (the dispatch-latency amortization the
    # epoch-window design exists for), without the full-epoch runtime.
    guard_lim = max(max_iters, 2 * WINDOW)

    def _ips(ft):
        tr = _make_trainer(headline_model, headline_strategy, ndev,
                           global_batch=global_batch, data_dir=data_dir,
                           log=lambda s: None,
                           limit_train_batches=guard_lim, ft=ft)
        return max(tr.steady_state_throughput(
                       max_iters=max_iters, window_iters="epoch")[1]
                   for _ in range(2))

    log("[bench] robustness: guard overhead (nonfinite=skip vs off)")
    base_ips = _ips(None)
    guard_ips = _ips(FTConfig(nonfinite="skip"))
    out["guard_overhead"] = {
        "unguarded_images_per_sec_per_chip": round(base_ips, 2),
        "guarded_images_per_sec_per_chip": round(guard_ips, 2),
        "guard_cost_pct": round((1.0 - guard_ips / base_ips) * 100.0, 2),
    }

    # Degraded vs healthy staging on the host-augment path.  Short cap:
    # the ratio stabilizes within a couple of windows and the degraded
    # path is serial by construction.
    lim = min(max_iters, 49)

    def _host_ips(ft):
        tr = _make_trainer(headline_model, headline_strategy, ndev,
                           global_batch=global_batch, data_dir=data_dir,
                           log=lambda s: None, host_augment=True,
                           limit_train_batches=lim, ft=ft)
        nfull, tail_per = tr._per_rank_batch_counts()
        images = (min(lim, nfull) * global_batch
                  + (tail_per * tr.world if lim > nfull and tail_per else 0))
        tr.train_model(0)   # compile + warm
        best = 0.0
        for _ in range(2):
            t0 = _time.time()
            tr.train_model(0)
            best = max(best, images / (_time.time() - t0))
        return best / ndev

    log("[bench] robustness: staging healthy vs degraded (host-augment)")
    healthy = _host_ips(None)
    degraded = _host_ips(FTConfig(degrade_staging=True))
    out["staging"] = {
        "limit_train_batches": lim,
        "healthy_images_per_sec_per_chip": round(healthy, 2),
        "degraded_images_per_sec_per_chip": round(degraded, 2),
        "degraded_fraction_of_healthy": round(degraded / healthy, 3),
    }

    # Emergency-checkpoint wall clock: what going down (save) and coming
    # back (restore) cost, on the real model state; plus the replay
    # accounting that motivates step-level checkpoints at all.
    log("[bench] robustness: emergency checkpoint save/restore wall clock")
    from cs744_ddp_tpu.train.checkpoint import CheckpointManager
    tr = _make_trainer(headline_model, headline_strategy, ndev,
                       global_batch=global_batch, data_dir=data_dir,
                       log=lambda s: None)
    nbatches, _ = tr._per_rank_batch_counts()
    with tempfile.TemporaryDirectory() as ckdir:
        mngr = CheckpointManager(ckdir)
        try:
            t0 = _time.time()
            mngr.save_mid_epoch(0, nbatches // 2, tr.state)
            save_s = _time.time() - t0
            t0 = _time.time()
            mngr.restore_mid_epoch(tr.state)
            restore_s = _time.time() - t0
        finally:
            mngr.close()
    out["checkpoint"] = {
        "emergency_save_s": round(save_s, 3),
        "mid_epoch_restore_s": round(restore_s, 3),
        "steps_lost_with_step_ckpt": 0,
        "steps_lost_epoch_only_worst_case": nbatches,
    }

    # End-to-end skip-policy demo: one window with a NaN gradient injected
    # at an exact step — the update is dropped, the run stays finite.
    log("[bench] robustness: non-finite skip demo (chaos nonfinite_grad:2)")
    trg = _make_trainer(headline_model, headline_strategy, ndev,
                        global_batch=global_batch, data_dir=data_dir,
                        log=lambda s: None, limit_train_batches=WINDOW,
                        ft=FTConfig(nonfinite="skip",
                                    chaos=ChaosPlan.parse(
                                        ["nonfinite_grad:2"])))
    timers = trg.train_model(0)
    out["nonfinite_skip"] = {
        "chaos": "nonfinite_grad:2",
        "updates_skipped": trg._epoch_nf_skipped,
        "final_loss_finite": bool(np.isfinite(timers.losses[-1])),
    }
    return out


def _startup_cold_warm(log, *, model: str, buckets, seed: int,
                       timeout_s: float = 900.0) -> dict:
    """COLD vs WARM engine startup, each measured in a FRESH subprocess
    (``python -m cs744_ddp_tpu.serve.demo --startup-probe``) sharing one
    executable-cache dir: run 1 populates it (cold), run 2 loads from it
    (warm).  Subprocesses because in-process \"restarts\" inherit jax's
    in-memory jit caches and would overstate the warm win.

    Falls back to in-process measurement (two engines, fresh cache dir)
    when the subprocess path is unavailable — e.g. a test-registered model
    the child interpreter has never heard of — and labels the result's
    ``method`` accordingly.  Note the repo-wide persistent XLA cache stays
    active in BOTH runs (it is process-global state, exactly what a server
    restart on this host would see), so \"cold\" means \"no serialized
    executables\", not \"no compile cache\" — ``cold_includes_xla_cache``
    records this."""
    import subprocess
    import tempfile

    bucket_spec = ",".join(str(b) for b in buckets)
    repo = os.path.dirname(os.path.abspath(__file__))

    def _probe(cache_dir: str):
        cmd = [sys.executable, "-m", "cs744_ddp_tpu.serve.demo",
               "--startup-probe", "--model", model,
               "--buckets", bucket_spec, "--cache-dir", cache_dir,
               "--seed", str(seed)]
        proc = subprocess.run(cmd, cwd=repo, capture_output=True,
                              text=True, timeout=timeout_s)
        if proc.returncode != 0:
            return None, proc.stderr.strip().splitlines()[-1:] or ["?"]
        return json.loads(proc.stdout.strip().splitlines()[-1]), None

    with tempfile.TemporaryDirectory() as cache_dir:
        log(f"[bench] serving: cold startup probe ({model}, subprocess)")
        cold, err = _probe(cache_dir)
        if cold is not None:
            log("[bench] serving: warm startup probe (same cache dir)")
            warm, err = _probe(cache_dir)
        if cold is None or warm is None:
            # Child interpreter can't build this model (or died): measure
            # in-process — still two engine builds against one cache dir.
            log(f"[bench] serving: subprocess probe unavailable "
                f"({err}); measuring startup in-process")
            from cs744_ddp_tpu.serve import InferenceEngine
            cold = InferenceEngine(model, buckets=buckets, seed=seed,
                                   cache_dir=cache_dir).startup()
            warm = InferenceEngine(model, buckets=buckets, seed=seed,
                                   cache_dir=cache_dir).startup()
            method = "in_process"
        else:
            method = "subprocess"
    out = {
        "method": method,
        "cold_s": cold["startup_s"],
        "warm_s": warm["startup_s"],
        "warm_was_all_cache": warm["warm"],
        "warm_lt_half_cold": warm["startup_s"] < 0.5 * cold["startup_s"],
        "cold_includes_xla_cache": True,
        "executable_serialization": cold["executable_cache"]["supported"],
        "cold_per_bucket": cold["per_bucket"],
        "warm_per_bucket": warm["per_bucket"],
    }
    if not out["warm_lt_half_cold"]:
        log(f"[bench] serving: WARNING warm startup {out['warm_s']}s is "
            f"not < 0.5 x cold {out['cold_s']}s")
    return out


def run_serving(log, *, model: str = "vgg11", buckets=None,
                loads=(5.0, 20.0), n_requests: int = 100,
                max_wait_ms: float = 5.0, seed: int = 0,
                dispatch_reps: int = 20, dispatch_budget_s: float = 3.0,
                precision: str = "f32", startup_probe: bool = True) -> dict:
    """The serving fast path's numbers (``cs744_ddp_tpu/serve/``), measured:

    * ``throughput_vs_bucket`` — for every rung of the executable ladder:
      ``per_dispatch_ms`` (one FENCED ``infer_counts`` call: staging +
      dispatch + logits fetch — what a lone request experiences) and
      ``device_program_ms`` (back-to-back enqueues on the same staged
      buffer, blocked once at the end, divided by the rep count — the
      device program's amortized cost with dispatch overhead overlapped).
      The spread between the two IS the per-dispatch tax (~100 ms on the
      tunneled TPU host, BASELINE.md); ``images_per_sec`` uses the
      amortized figure, the saturated-pipeline ceiling.
    * ``latency`` — client-side p50/p95/p99 under a seeded OPEN-LOOP
      arrival trace through the bounded-queue micro-batcher, one entry per
      offered load (requests/sec) — the knee where queueing delay takes
      over is the capacity statement.
    * ``startup`` — cold vs warm engine startup (``_startup_cold_warm``):
      the executable ladder compiled from scratch vs deserialized from the
      warm-start cache, fresh subprocess each.

    Standalone-callable, same contract as ``run_robustness``: the
    committed artifact's serving section can be refreshed without
    re-running the training-side sections."""
    import time as _time

    import jax
    import numpy as np

    from cs744_ddp_tpu.obs import Telemetry
    from cs744_ddp_tpu.serve import BUCKETS, InferenceEngine
    from cs744_ddp_tpu.serve.demo import request_pool, run_demo

    log = log or (lambda s: print(s, file=sys.stderr))
    buckets = tuple(buckets) if buckets else BUCKETS
    tel = Telemetry()   # in-memory; summary attached below
    log(f"[bench] serving: building {model} ladder over buckets "
        f"{buckets} ({precision})")
    engine = InferenceEngine(model, buckets=buckets, seed=seed,
                             precisions=(precision,), telemetry=tel)
    ladder = engine.startup()
    out = {
        "backend": jax.default_backend(),
        "model": model,
        "buckets": list(buckets),
        "precision": precision,
        "ladder_startup": ladder,
    }

    # Static audit of the executable ladder we are about to measure: each
    # bucket's program must be collective-free, precision-clean and
    # constant-lean (analysis/audit.py).  Tolerant — the audit must never
    # kill a serving bench whose measurements matter more than its paper
    # trail.
    try:
        from cs744_ddp_tpu.analysis import audit as _auditlib
        audit_res = _auditlib.AuditResult(
            reports=_auditlib.audit_serving(engine=engine,
                                            precision=precision))
        out["audit"] = audit_res.summary()
        log(f"[bench] serving: audit "
            f"{'CLEAN' if audit_res.clean else 'DIRTY'} over "
            f"{len(audit_res.reports)} bucket programs")
    except Exception as e:   # noqa: BLE001 - advisory section
        log(f"[bench] serving: ladder audit failed ({e!r}); omitted")

    # Throughput-vs-bucket curve.  The rep count adapts to the measured
    # per-dispatch time so a slow rung (vgg11/256 on a 1-core CPU host)
    # costs ~dispatch_budget_s, not dispatch_reps x seconds.
    pool = request_pool(max(buckets), seed=seed + 7)
    curve = {}
    for b in buckets:
        images = pool.images[:b]
        labels = pool.labels[:b]
        engine.infer_counts(images, labels, precision=precision)  # warm
        per_disp = float("inf")
        for _ in range(3):
            t0 = _time.time()
            engine.infer_counts(images, labels, precision=precision)
            per_disp = min(per_disp, _time.time() - t0)
        reps = max(3, min(dispatch_reps, int(dispatch_budget_s / per_disp)
                          if per_disp > 0 else dispatch_reps))
        ex = engine._executable(b, precision)
        staged = engine._pad_stage(images, b)
        padded_labels = np.asarray(labels, np.int32)
        res = ex(engine.params, engine.bn_state, staged, padded_labels)
        jax.block_until_ready(res)
        t0 = _time.time()
        for _ in range(reps):
            res = ex(engine.params, engine.bn_state, staged, padded_labels)
        jax.block_until_ready(res)
        prog = (_time.time() - t0) / reps
        curve[str(b)] = {
            "per_dispatch_ms": round(per_disp * 1e3, 3),
            "device_program_ms": round(prog * 1e3, 3),
            "images_per_sec": round(b / prog, 2),
            "reps": reps,
        }
        log(f"[bench] serving: bucket {b}: {curve[str(b)]['images_per_sec']}"
            f" img/s amortized, {curve[str(b)]['per_dispatch_ms']} ms/dispatch")
    out["throughput_vs_bucket"] = curve

    # Open-loop latency at the offered loads (seeded trace, shared pool).
    out["latency"] = {}
    for rps in loads:
        log(f"[bench] serving: open-loop trace at {rps:g} req/s "
            f"({n_requests} requests)")
        out["latency"][f"{rps:g}rps"] = run_demo(
            engine, n_requests=n_requests, offered_rps=rps, seed=seed,
            max_wait_ms=max_wait_ms, pool=pool, precision=precision)

    if startup_probe:
        out["startup"] = _startup_cold_warm(log, model=model,
                                            buckets=buckets, seed=seed)
    out["telemetry_summary"] = tel.finalize()
    return out


def _servenet_factory():
    """conv(3->8)+BN+relu+pool(4x)+fc — the serving-load workload model.

    The load rows offer thousands of requests/sec; the flagship vgg11
    ladder serves ~0.7 req/s on this host (run_serving), so the load
    sections would measure nothing but one giant queue.  Same layer kinds
    as the real models (and as the tests' tiny_cnn — redefined here
    because tests/ is not importable from the bench), registered under
    ``servenet`` via the models registry like any user model."""
    import jax
    import jax.numpy as jnp

    from cs744_ddp_tpu.models import layers

    def init_fn(key, dtype=jnp.float32):
        k1, k2 = jax.random.split(key)
        params = {"conv": layers.conv2d_init(k1, 3, 8, 3, dtype)}
        params["bn"], bn_state = layers.batchnorm_init(8, dtype)
        params["fc"] = layers.linear_init(k2, 8 * 8 * 8, 10, dtype)
        return params, {"bn": bn_state}

    def apply_fn(params, state, x, *, train):
        y = layers.conv2d_apply(params["conv"], x)
        y, new_bn = layers.batchnorm_apply(params["bn"], state["bn"], y,
                                           train=train)
        y = layers.relu(y)
        y = layers.maxpool2x2(layers.maxpool2x2(y))  # 32 -> 8
        y = y.reshape(y.shape[0], -1)
        return layers.linear_apply(params["fc"], y), {"bn": new_bn}

    return init_fn, apply_fn


def run_serving_load(log, *, model: str = "servenet", buckets=None,
                     replica_counts=(1, 2, 4, 8),
                     burst_requests: int = 500, burst_rps: float = 8000.0,
                     burst_slo_ms: float = 2000.0,
                     burst_sizes=(4, 8, 8, 16), queue_images: int = 256,
                     curve_loads=(250.0, 1000.0, 2000.0, 4000.0),
                     curve_requests: int = 400, curve_slo_ms: float = 500.0,
                     overload_tiers=((0, 2, 1000.0), (1, 5, 500.0),
                                     (2, 3, 800.0)),
                     overload_requests: int = 2400,
                     overload_queue_images: int = 4096,
                     matched_rps: float = 400.0,
                     seed: int = 0, precision: str = "f32") -> dict:
    """The serving tier under load (``serve/`` round 9): replicated
    device-pinned engines behind the least-loaded router, driven by
    seeded open-loop traces through the in-process client.

    * ``replica_scaling`` — goodput at a FIXED SLO as replicas grow
      1->2->4->8.  PROVENANCE: this host time-shares every replica over
      one physical core, so device throughput CANNOT scale with replica
      count — what scales is bounded-queue admission capacity (each
      replica brings its own ``max_queue_images`` admission queue).  The
      row therefore offers a burst that over-runs a single replica's
      queue, and goodput is SLO-met completions per second of the fixed
      ``span + SLO`` observation window (same denominator every row) —
      the component of replica scale-out that survives the 1-core
      constraint.  On a real mesh the same row also scales service.
    * ``goodput_vs_offered`` — the saturation curve at the full replica
      set: goodput tracks offered load until the shared core saturates,
      then attainment falls and shedding/overload absorb the excess.
    * ``overload_2x`` — 2x the measured capacity, tiered traffic:
      priority-tier admission must hold top-tier attainment while
      deterministic shedding is confined to the lower tiers; the
      no-silent-drop accounting (one terminal reply per request) rides
      in the row.
    * ``continuous_vs_drain`` — virtual-time replay of a matched trace
      through ``plan_continuous`` vs ``plan_drain`` (the round-7
      MicroBatcher's coalesce-and-drain semantics) using the MEASURED
      per-bucket service model from the live rows: continuous batching
      must hold strictly lower p99 queue-wait at matched load.

    Standalone-callable, same contract as ``run_serving``."""
    import time as _time

    import jax

    from cs744_ddp_tpu import models
    from cs744_ddp_tpu.obs import NULL, Telemetry
    from cs744_ddp_tpu.serve import (BUCKETS, EngineReplica, LoopbackClient,
                                     ReplicaRouter, demo, plan_continuous,
                                     plan_drain, virtual_requests)

    log = log or (lambda s: print(s, file=sys.stderr))
    buckets = tuple(buckets) if buckets else BUCKETS
    if model == "servenet":
        models.register_model("servenet", _servenet_factory)
    devices = jax.devices()
    nmax = max(replica_counts)
    log(f"[bench] serving_load: building {nmax} {model} replicas over "
        f"{len(devices)} device(s)")
    t0 = _time.time()
    replicas = [EngineReplica(i, model=model,
                              device=devices[i % len(devices)],
                              buckets=buckets, precision=precision,
                              seed=seed, cost_prior=True,
                              max_queue_images=queue_images)
                for i in range(nmax)]
    for r in replicas:
        r.startup()
    build_s = _time.time() - t0
    pool = demo.request_pool(seed=seed + 123)
    out = {
        "backend": jax.default_backend(),
        "model": model,
        "buckets": list(buckets),
        "num_devices": len(devices),
        "replicas_built": nmax,
        "build_s": round(build_s, 3),
        "provenance": (
            "single-physical-core host (time-shared CPU mesh): aggregate "
            "device throughput is conserved across replica counts, so the "
            "replica_scaling row measures what replicas add on this host — "
            "bounded-queue admission capacity at a fixed SLO under a burst "
            "that over-runs one replica's queue; goodput is SLO-met "
            "completions per second of the fixed span+SLO window.  The "
            f"workload model is the small registered '{model}' CNN: the "
            "flagship vgg11 ladder serves <1 req/s here (see the serving "
            "section) and cannot exercise thousands-of-req/s traces."),
    }

    def _replay(n_replicas, trace, telemetry=None, timeout_s=60.0):
        router = ReplicaRouter(replicas[:n_replicas], telemetry=telemetry)
        with router:
            client = LoopbackClient(router)
            stats = demo.replay_load(client, trace, pool=pool, seed=seed,
                                     drain_timeout_s=timeout_s)
        return stats, router.stats()

    def _row(stats, window_s=None):
        ok = sum(c["ok"] for c in stats["by_tier"].values())
        row = {
            "offered_rps": stats["offered_rps"],
            "goodput_rps": stats["goodput_rps"],
            "goodput_ips": stats["goodput_ips"],
            "attainment": stats["attainment"],
            "shed": stats["shed"],
            "overload": stats["overload"],
            "replies": stats["replies"],
            "unresolved": stats["unresolved"],
        }
        if window_s is not None:
            row["goodput_rps_window"] = round(ok / window_s, 2)
        if "queue_wait_ms" in stats:
            row["queue_wait_ms"] = stats["queue_wait_ms"]
        return row

    # Replica scaling at a fixed SLO (burst trace; see docstring).
    burst = demo.synthetic_load_trace(
        burst_requests, offered_rps=burst_rps, seed=seed,
        size_choices=burst_sizes, tiers=((0, 1, burst_slo_ms),))
    span_s = burst[-1][0]
    window_s = span_s + burst_slo_ms / 1e3
    scaling = {"offered_rps": round(burst_requests / max(span_s, 1e-9), 1),
               "slo_ms": burst_slo_ms, "window_s": round(window_s, 3),
               "per_replica_queue_images": queue_images, "rows": {}}
    for n in replica_counts:
        log(f"[bench] serving_load: scaling row, {n} replica(s), "
            f"{burst_requests} reqs @ {scaling['offered_rps']} rps, "
            f"SLO {burst_slo_ms:g} ms")
        stats, _rs = _replay(n, burst,
                             timeout_s=2.0 + 3.0 * burst_slo_ms / 1e3)
        scaling["rows"][str(n)] = _row(stats, window_s=window_s)
    g1 = scaling["rows"][str(replica_counts[0])]["goodput_rps_window"]
    g8 = scaling["rows"][str(nmax)]["goodput_rps_window"]
    scaling["goodput_scale_1_to_max"] = round(g8 / max(g1, 1e-9), 2)
    out["replica_scaling"] = scaling
    log(f"[bench] serving_load: goodput@SLO x"
        f"{scaling['goodput_scale_1_to_max']} from 1->{nmax} replicas")

    # Goodput-vs-offered-load saturation curve at the full replica set.
    curve = {"replicas": nmax, "slo_ms": curve_slo_ms, "points": {}}
    for rps in curve_loads:
        nreq = max(curve_requests, min(int(rps), 2 * curve_requests))
        trace = demo.synthetic_load_trace(
            nreq, offered_rps=rps, seed=seed + 1,
            tiers=((0, 1, curve_slo_ms),))
        log(f"[bench] serving_load: curve point {rps:g} rps ({nreq} reqs)")
        stats, _rs = _replay(nmax, trace)
        curve["points"][f"{rps:g}"] = _row(stats)
    out["goodput_vs_offered"] = curve
    cap_rps = max(p["goodput_rps"] for p in curve["points"].values())

    # 2x overload, tiered: top-tier attainment holds, shedding confined
    # to the lower tiers, every request gets a terminal reply.  The
    # tier-0 SLO sits above the p95 of one CONTENDED dispatch (8
    # replica threads share this host's core, so a ~60ms solo dispatch
    # runs ~300ms under contention) — below that floor no admission
    # policy can meet the deadline and the row measures the host, not
    # the scheduler.  The lower-tier SLOs sit BELOW the 2x backlog's
    # measured queue-wait tail, forcing real shed decisions; tier-0
    # jumps the queue at every admission, so its deadline holds while
    # the tiers beneath it absorb the overload.
    for r in replicas:
        r.scheduler.max_queue_images = overload_queue_images
    over_rps = 2.0 * cap_rps
    tel = Telemetry()   # in-memory; the slo summary rides in the row
    for r in replicas:
        r.scheduler.telemetry = tel
    log(f"[bench] serving_load: overload row at {over_rps:.0f} rps "
        f"(2x measured capacity {cap_rps:.0f} rps)")
    trace = demo.synthetic_load_trace(overload_requests,
                                      offered_rps=over_rps, seed=seed + 2,
                                      tiers=overload_tiers)
    stats, _rs = _replay(nmax, trace, telemetry=tel)
    for r in replicas:
        r.scheduler.telemetry = NULL
        r.scheduler.max_queue_images = queue_images
    shed_by_tier = {str(t): c["shed"] for t, c in stats["by_tier"].items()}
    top = min(stats["by_tier"])
    out["overload_2x"] = {
        "offered_rps": stats["offered_rps"],
        "capacity_rps": round(cap_rps, 2),
        "tiers": [list(t) for t in overload_tiers],
        "by_tier": {str(t): c for t, c in stats["by_tier"].items()},
        "top_tier_attainment": stats["by_tier"][top]["attainment"],
        "shed_by_tier": shed_by_tier,
        "total_shed": sum(shed_by_tier.values()),
        "sheds_confined_to_lower_tiers": (
            shed_by_tier.get(str(top), 0) == 0
            and sum(shed_by_tier.values()) > 0),
        "accounting": {k: stats[k] for k in
                       ("replies", "unresolved", "unique_traces", "traced")},
        "queue_wait_ms": stats.get("queue_wait_ms"),
        "telemetry_summary": tel.finalize(),
    }
    if out["overload_2x"]["top_tier_attainment"] < 0.95:
        log(f"[bench] serving_load: WARNING top-tier attainment "
            f"{out['overload_2x']['top_tier_attainment']} < 0.95 under "
            "2x overload")
    if out["overload_2x"]["total_shed"] == 0:
        log("[bench] serving_load: WARNING overload row shed nothing — "
            "the shed-confinement claim is vacuous at these SLOs")

    # Continuous batching vs the drain baseline: virtual-time replay of a
    # matched trace with the MEASURED service model (deterministic given
    # the measured per-bucket times; no thread scheduling noise).
    svc = replicas[0].scheduler.svc
    vtrace = demo.synthetic_load_trace(400, offered_rps=matched_rps,
                                       seed=seed + 3,
                                       tiers=((0, 1, curve_slo_ms),))
    cont = plan_continuous(virtual_requests(vtrace), buckets=buckets,
                           predict_s=svc.predict, shed=False)
    drain = plan_drain(virtual_requests(vtrace), buckets=buckets,
                       predict_s=svc.predict)
    keep = ("dispatches", "served", "p50_wait_ms", "p99_wait_ms")
    out["continuous_vs_drain"] = {
        "matched_rps": matched_rps,
        "service_model_ms": {str(b): round(svc.predict(b) * 1e3, 4)
                             for b in buckets},
        "continuous": {k: cont[k] for k in keep},
        "drain": {k: drain[k] for k in keep},
        "continuous_p99_lower":
            cont["p99_wait_ms"] < drain["p99_wait_ms"],
    }
    log(f"[bench] serving_load: p99 queue-wait continuous "
        f"{cont['p99_wait_ms']} ms vs drain {drain['p99_wait_ms']} ms "
        f"at {matched_rps:g} rps")
    return out


def run_pipeline(log, *, model: str = "servenet", buckets=(8, 32),
                 steady_reps: int = 40, n_replicas: int = 2,
                 capacity_loads=(600.0, 1200.0, 2000.0),
                 capacity_requests: int = 400,
                 capacity_slo_ms: float = 500.0,
                 seed: int = 0, precision: str = "f32") -> dict:
    """The dispatch pipeline's cost sheet (``serve/`` round 14): what
    double-buffered two-slot dispatch buys over the serial
    dispatch-fence-reply loop, measured three ways.

    * ``per_dispatch`` — per ladder rung: one FENCED serial dispatch
      (stage + dispatch + logits fetch, what round 13 charged every
      batch) vs the PIPELINED steady-state per-dispatch time (two
      ``infer_counts_async`` handles in flight, completions resolved in
      issue order) vs the back-to-back ``device_program_ms`` floor.
      ``gap_closed`` is the fraction of the serial-over-floor gap the
      overlap recovers.
    * ``capacity`` — goodput under seeded open-loop traces with the
      scheduler pipeline ON vs OFF (same replica layout, same traces;
      OFF is exactly the round-13 serial worker).  The acceptance row:
      pipelined capacity vs the round-9 ~440 req/s figure.
    * ``waterfall`` — the pipelined capacity point re-run under a
      recording telemetry: staging / device-compute / fetch stage
      split, the occupancy distribution from the ``serve_inflight``
      gauges (bounded by ``PIPELINE_SLOTS``), and the per-bucket
      measured-over-cost-prior ratio (round 12 measured 3.25x on
      bucket 8 — the per-dispatch tax the overlap is built to hide;
      with occupancy-honest ``serve_dispatch`` spans the ratio
      converges toward the device-program floor).

    Standalone-callable, same contract as ``run_serving_load``."""
    import time as _time

    import jax
    import numpy as np

    from cs744_ddp_tpu import models
    from cs744_ddp_tpu.obs import Telemetry, aggregate as _agg
    from cs744_ddp_tpu.obs.telemetry import percentile as _pctl
    from cs744_ddp_tpu.serve import (PIPELINE_SLOTS, EngineReplica,
                                     InferenceEngine, LoopbackClient,
                                     ReplicaRouter, demo)
    from cs744_ddp_tpu.serve.scheduler import cost_model_weights

    log = log or (lambda s: print(s, file=sys.stderr))
    buckets = tuple(buckets)
    if model == "servenet":
        models.register_model("servenet", _servenet_factory)
    out = {"backend": jax.default_backend(), "model": model,
           "buckets": list(buckets), "pipeline_slots": PIPELINE_SLOTS}

    # -- per-dispatch: serial vs pipelined vs device-program floor -------
    log(f"[bench] pipeline: building {model} ladder over {buckets} "
        f"({precision})")
    engine = InferenceEngine(model, buckets=buckets, seed=seed,
                             precisions=(precision,))
    engine.startup()
    pool = demo.request_pool(max(buckets), seed=seed + 7)
    per = {}
    for b in buckets:
        images = pool.images[:b]
        labels = pool.labels[:b]
        engine.infer_counts(images, labels, precision=precision)  # warm
        serial = float("inf")
        for _ in range(3):
            t0 = _time.time()
            engine.infer_counts(images, labels, precision=precision)
            serial = min(serial, _time.time() - t0)
        # Device-program floor: back-to-back enqueues on one staged
        # buffer, blocked once at the end (same protocol as run_serving).
        ex = engine._executable(b, precision)
        staged = engine._pad_stage(images, b)
        padded_labels = np.asarray(labels, np.int32)
        res = ex(engine.params, engine.bn_state, staged, padded_labels)
        jax.block_until_ready(res)
        t0 = _time.time()
        for _ in range(steady_reps):
            res = ex(engine.params, engine.bn_state, staged, padded_labels)
        jax.block_until_ready(res)
        floor = (_time.time() - t0) / steady_reps
        # Pipelined steady state: keep PIPELINE_SLOTS handles in flight,
        # complete in issue order — the scheduler's exact dispatch shape.
        handles = [engine.infer_counts_async(images, labels,
                                             precision=precision)]
        engine.complete(handles.pop(0))   # warm the async path
        t0 = _time.time()
        for _ in range(steady_reps):
            handles.append(engine.infer_counts_async(
                images, labels, precision=precision))
            if len(handles) == PIPELINE_SLOTS:
                engine.complete(handles.pop(0))
        while handles:
            engine.complete(handles.pop(0))
        pipe = (_time.time() - t0) / steady_reps
        gap = serial - floor
        per[str(b)] = {
            "serial_per_dispatch_ms": round(serial * 1e3, 3),
            "pipelined_per_dispatch_ms": round(pipe * 1e3, 3),
            "device_program_ms": round(floor * 1e3, 3),
            "reps": steady_reps,
            "gap_closed": round((serial - pipe) / gap, 4) if gap > 0
            else None,
        }
        log(f"[bench] pipeline: bucket {b}: serial "
            f"{per[str(b)]['serial_per_dispatch_ms']} ms -> pipelined "
            f"{per[str(b)]['pipelined_per_dispatch_ms']} ms (floor "
            f"{per[str(b)]['device_program_ms']} ms)")
    out["per_dispatch"] = per

    # -- capacity: pipeline ON vs OFF over the same seeded traces --------
    devices = jax.devices()
    pool_cap = demo.request_pool(seed=seed + 123)
    sizes = tuple(s for s in demo.SIZE_CHOICES if s <= buckets[-1])
    traces = {f"{rps:g}": demo.synthetic_load_trace(
        max(capacity_requests, min(int(rps), 2 * capacity_requests)),
        offered_rps=rps, seed=seed + 1, size_choices=sizes,
        tiers=((0, 1, capacity_slo_ms),)) for rps in capacity_loads}

    def _capacity_rows(pipeline, telemetry=None):
        reps = [EngineReplica(i, model=model,
                              device=devices[i % len(devices)],
                              buckets=buckets, precision=precision,
                              seed=seed, cost_prior=True,
                              telemetry=telemetry, pipeline=pipeline)
                for i in range(n_replicas)]
        for r in reps:
            r.startup()
        points = {}
        for key, trace in traces.items():
            router = ReplicaRouter(reps, telemetry=telemetry)
            with router:
                client = LoopbackClient(router)
                stats = demo.replay_load(client, trace, pool=pool_cap,
                                         seed=seed, drain_timeout_s=60.0)
            points[key] = {
                "offered_rps": stats["offered_rps"],
                "goodput_rps": stats["goodput_rps"],
                "attainment": stats["attainment"],
                "shed": stats["shed"],
                "queue_wait_ms": stats.get("queue_wait_ms"),
            }
            log(f"[bench] pipeline: capacity {key} rps pipeline="
                f"{'on' if pipeline else 'off'}: goodput "
                f"{stats['goodput_rps']} rps, attainment "
                f"{stats['attainment']}")
        return points

    log(f"[bench] pipeline: capacity A/B, {n_replicas} replica(s), "
        f"SLO {capacity_slo_ms:g} ms")
    rows_off = _capacity_rows(False)
    rows_on = _capacity_rows(True)
    cap_off = max(p["goodput_rps"] for p in rows_off.values())
    cap_on = max(p["goodput_rps"] for p in rows_on.values())
    out["capacity"] = {
        "replicas": n_replicas,
        "slo_ms": capacity_slo_ms,
        "pipeline_off": rows_off,
        "pipeline_on": rows_on,
        "capacity_rps_off": cap_off,
        "capacity_rps_on": cap_on,
        "round9_capacity_rps": 441.6,
        "beats_round9": cap_on > 441.6,
    }
    log(f"[bench] pipeline: capacity off {cap_off} vs on {cap_on} rps "
        f"(round-9 figure 441.6)")

    # -- waterfall at the pipelined capacity point -----------------------
    best_key = max(rows_on, key=lambda k: rows_on[k]["goodput_rps"])
    log(f"[bench] pipeline: waterfall re-run at {best_key} rps "
        f"(recording telemetry)")
    tel = Telemetry()   # in-memory; events mirrored in tel.records
    reps = [EngineReplica(i, model=model,
                          device=devices[i % len(devices)],
                          buckets=buckets, precision=precision,
                          seed=seed, cost_prior=True,
                          telemetry=tel, pipeline=True)
            for i in range(n_replicas)]
    for r in reps:
        r.startup()
    prior_flops = cost_model_weights(reps[0].engine, precision)
    router = ReplicaRouter(reps, telemetry=tel)
    with router:
        client = LoopbackClient(router)
        demo.replay_load(client, traces[best_key], pool=pool_cap,
                         seed=seed, drain_timeout_s=60.0)
    events = list(tel.records)
    stage_ms = {}
    for e in events:
        if e.get("kind") == "span" and e.get("name") in (
                "serve_stage", "serve_dispatch", "serve_fetch"):
            stage_ms.setdefault(e["name"], []).append(e["dur_s"] * 1e3)
    occ = {}
    for e in events:
        if e.get("kind") == "gauge" and e.get("name") == "serve_inflight":
            v = int(e["value"])
            occ[v] = occ.get(v, 0) + 1
    nocc = sum(occ.values())
    by_bucket = {}
    for e in events:
        if e.get("kind") == "span" and e.get("name") == "serve_dispatch" \
                and "bucket" in e:
            by_bucket.setdefault(int(e["bucket"]), []).append(
                e["dur_s"] * 1e3)
    prior = _agg.fit_cost_prior(
        [{"bucket": b, "stages": {"device_compute": ms}}
         for b, v in by_bucket.items() for ms in v], prior_flops)
    out["waterfall"] = {
        "offered_rps_point": best_key,
        "stage_ms": {n: {"p50": round(_pctl(v, 50), 3),
                         "p99": round(_pctl(v, 99), 3),
                         "count": len(v)}
                     for n, v in sorted(stage_ms.items())},
        "occupancy": {str(k): round(occ[k] / nocc, 4)
                      for k in sorted(occ)} if nocc else {},
        "max_inflight": max(occ) if occ else 0,
        "inflight_bound_ok": (max(occ) if occ else 0) <= PIPELINE_SLOTS,
        "cost_prior": prior,
    }
    if prior:
        for b, rec in prior["by_bucket"].items():
            log(f"[bench] pipeline: bucket {b} measured/prior "
                f"{rec['measured_over_prior']} (round-12 bucket-8 "
                f"figure: 3.254)")
    out["note"] = (
        "single-host CPU backend: device compute and host staging share "
        "the same cores, so the overlap the pipeline exists for cannot "
        "be banked here (per_dispatch.gap_closed can go negative); the "
        "accounting contracts — occupancy bound, issue-order spans, "
        "bitwise parity with the serial path — are what this section "
        "pins, and capacity/cost-prior are tracked vs the committed "
        "round-9/12 figures")
    return out


def run_tracing(log, *, model: str = "servenet", buckets=(8, 32),
                capacity_requests: int = 400, capacity_rps: float = 440.0,
                capacity_slo_ms: float = 500.0, capacity_repeats: int = 3,
                subprocess_requests: int = 150,
                subprocess_rps: float = 120.0,
                seed: int = 0, precision: str = "f32") -> dict:
    """Distributed tracing under load (``obs/`` round 12): what the
    tentpole costs and what it reconstructs.

    * ``capacity`` — the round-9 capacity row (~440 req/s loopback
      replay) with tracing OFF vs ON (server spans + client root
      contexts + events.jsonl writes).  The pin: tracing costs <= 5%
      goodput.  Median of ``capacity_repeats`` runs each way, same
      seeded trace.
    * ``two_process`` — the acceptance scenario: a REAL second OS
      process (tools/serve_load.py replay ``--telemetry-out``) drives
      the socket front-end; both processes' event streams are merged by
      ``obs/aggregate.py`` into skew-corrected waterfalls.  Reported:
      clock-skew estimate (bounded by RTT), complete/orphaned trace
      counts, the waterfall-sum-vs-client-measured residual, the
      device-compute join against the HLO cost-model prior, and the
      aggregation wall clock.

    Standalone-callable, same contract as ``run_serving_load``."""
    import json as _json
    import subprocess
    import tempfile
    import time as _time

    import jax

    from cs744_ddp_tpu import models
    from cs744_ddp_tpu.obs import Telemetry, aggregate as _agg
    from cs744_ddp_tpu.serve import (EngineReplica, FrontendClient,
                                     LoopbackClient, ReplicaRouter,
                                     ServingFrontend, demo)
    from cs744_ddp_tpu.serve.scheduler import cost_model_weights

    log = log or (lambda s: print(s, file=sys.stderr))
    buckets = tuple(buckets)
    if model == "servenet":
        models.register_model("servenet", _servenet_factory)
    pool = demo.request_pool(seed=seed + 123)
    sizes = tuple(s for s in demo.SIZE_CHOICES if s <= buckets[-1])
    trace = demo.synthetic_load_trace(
        capacity_requests, offered_rps=capacity_rps, seed=seed,
        size_choices=sizes, tiers=((0, 1, capacity_slo_ms),))

    def _build(telemetry=None):
        rep = EngineReplica(0, model=model, buckets=buckets,
                            precision=precision, seed=seed,
                            telemetry=telemetry, cost_prior=True)
        rep.startup()
        return rep

    def _goodput(rep, telemetry_client=None):
        router = ReplicaRouter([rep], telemetry=rep.telemetry)
        with router:
            client = LoopbackClient(router, telemetry=telemetry_client)
            # Warm every bucket outside the measured window.
            import numpy as _np
            for b in buckets:
                LoopbackClient(router).submit(
                    _np.zeros((b, 32, 32, 3), _np.uint8), tier=0,
                    slo_ms=60_000.0).result(timeout=120)
            stats = demo.replay_load(client, trace, pool=pool, seed=seed,
                                     drain_timeout_s=60.0)
        return stats

    out = {"backend": jax.default_backend(), "model": model,
           "buckets": list(buckets)}

    # -- capacity: tracing off vs on -------------------------------------
    log(f"[bench] tracing: capacity {capacity_requests} reqs @ "
        f"{capacity_rps:g} rps, {capacity_repeats}x off vs on")
    rep_off = _build(telemetry=None)
    off_runs = [_goodput(rep_off) for _ in range(capacity_repeats)]
    off = sorted(off_runs, key=lambda s: s["goodput_rps"])[len(off_runs) // 2]
    on_runs = []
    with tempfile.TemporaryDirectory() as td:
        for i in range(capacity_repeats):
            stel = Telemetry(os.path.join(td, f"srv{i}"))
            ctel = Telemetry(os.path.join(td, f"cli{i}"))
            rep_on = _build(telemetry=stel)
            on_runs.append(_goodput(rep_on, telemetry_client=ctel))
            stel.finalize()
            ctel.finalize()
    on = sorted(on_runs, key=lambda s: s["goodput_rps"])[len(on_runs) // 2]
    overhead = 1.0 - on["goodput_rps"] / max(off["goodput_rps"], 1e-9)
    out["capacity"] = {
        "offered_rps": off["offered_rps"],
        "slo_ms": capacity_slo_ms,
        "tracing_off": {"goodput_rps": off["goodput_rps"],
                        "attainment": off["attainment"],
                        "runs": [s["goodput_rps"] for s in off_runs]},
        "tracing_on": {"goodput_rps": on["goodput_rps"],
                       "attainment": on["attainment"],
                       "runs": [s["goodput_rps"] for s in on_runs]},
        "overhead_frac": round(overhead, 4),
        "overhead_budget": 0.05,
        "within_budget": overhead <= 0.05,
    }
    log(f"[bench] tracing: goodput off {off['goodput_rps']} vs on "
        f"{on['goodput_rps']} rps -> overhead {overhead:.1%}")
    if overhead > 0.05:
        log(f"[bench] tracing: WARNING overhead {overhead:.1%} exceeds "
            "the 5% budget")

    # -- two OS processes -> one skew-corrected waterfall ----------------
    log(f"[bench] tracing: two-process run, serve_load.py subprocess "
        f"{subprocess_requests} reqs @ {subprocess_rps:g} rps")
    with tempfile.TemporaryDirectory() as td:
        srv_dir = os.path.join(td, "server")
        cli_dir = os.path.join(td, "client")
        stel = Telemetry(srv_dir)
        rep = _build(telemetry=stel)
        prior_flops = cost_model_weights(rep.engine, precision)
        router = ReplicaRouter([rep], telemetry=stel)
        replay = None
        with router:
            with ServingFrontend(router, telemetry=stel) as fe:
                import numpy as _np
                with FrontendClient(fe.address) as warm:
                    for b in buckets:
                        warm.submit(_np.zeros((b, 32, 32, 3), _np.uint8),
                                    tier=0, slo_ms=60_000.0).result(120)
                proc = subprocess.run(
                    [sys.executable,
                     os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                  "tools", "serve_load.py"),
                     "replay", "--port", str(fe.address[1]),
                     "--rps", f"{subprocess_rps:g}",
                     "--requests", str(subprocess_requests),
                     "--max-size", str(buckets[-1]),
                     "--seed", str(seed + 7),
                     "--telemetry-out", cli_dir, "--timeout", "120"],
                    capture_output=True, text=True, timeout=300)
                if proc.returncode == 0:
                    replay = _json.loads(proc.stdout.strip().splitlines()[-1])
                else:
                    log("[bench] tracing: WARNING replay subprocess failed: "
                        + proc.stderr[-500:])
        stel.finalize()
        t0 = _time.time()
        report = _agg.aggregate_run_dirs([srv_dir, cli_dir],
                                         prior_flops=prior_flops,
                                         max_waterfalls=2)
        agg_wall_s = _time.time() - t0
    two = {
        "replay": ({k: replay[k] for k in ("n_requests", "goodput_rps",
                                           "attainment")}
                   if replay else None),
        "aggregate_wall_s": round(agg_wall_s, 4),
        "traces": report["traces"],
        "complete": report["complete"],
        "orphaned": report["orphaned"],
        "skew": {n: p for n, p in report["processes"].items()
                 if p["skew_estimated"] and p["skew_pairs"]},
        "stage_ms": report["stage_ms"],
        "residual_ms": report.get("client_minus_stages_ms"),
        "cost_prior": report.get("cost_prior"),
        "waterfall_example": (report["waterfalls"][0]
                              if report["waterfalls"] else None),
    }
    out["two_process"] = two
    if two["residual_ms"]:
        log(f"[bench] tracing: {two['complete']} complete waterfalls, "
            f"client-minus-stages residual p50 "
            f"{two['residual_ms']['p50']} ms, aggregation "
            f"{agg_wall_s * 1e3:.0f} ms")
    return out


def run_hotswap(log, *, model: str = "servenet", buckets=None,
                n_replicas: int = 2, n_requests: int = 400,
                offered_rps: float = 600.0, slo_ms: float = 2000.0,
                queue_images: int = 4096, publishes_per_row: int = 3,
                seed: int = 0, precision: str = "f32") -> dict:
    """Train-to-serve weight hot-swap under load (``publish/`` round 10).

    One steady row (no publishes) and two swap rows (rolling vs
    all-at-once) replay the SAME seeded open-loop trace through the
    replicated router while a background thread publishes fresh weight
    bundles at fixed fractions of the trace span and a ``WeightWatcher``
    installs each one at the schedulers' dispatch boundaries:

    * ``swap_ms`` p50/p99/max — per-replica publish-pointer-seen ->
      flip-landed latency from the watcher's own samples;
    * ``in_flight_at_publish`` — queued images + predicted outstanding
      seconds sampled across all replicas at each publish instant (the
      work the swap must not tear);
    * ``goodput_dip_pct`` — each swap row's goodput vs the steady row at
      matched offered load (what a swap costs the SLO);
    * ``recompiles`` — growth of every engine's executable cache across
      the row; the AOT ladder treats weights as arguments, so this is
      pinned at 0 (zero_recompiles rides in the section).

    Standalone-callable, same contract as ``run_serving_load``."""
    import tempfile
    import threading
    import time as _time

    import jax
    import numpy as np

    from cs744_ddp_tpu import models
    from cs744_ddp_tpu.models import get_model
    from cs744_ddp_tpu.publish import WeightPublisher, WeightWatcher
    from cs744_ddp_tpu.serve import (BUCKETS, EngineReplica, LoopbackClient,
                                     ReplicaRouter, demo)
    from cs744_ddp_tpu.train.step import init_train_state

    log = log or (lambda s: print(s, file=sys.stderr))
    buckets = tuple(buckets) if buckets else BUCKETS
    if model == "servenet":
        models.register_model("servenet", _servenet_factory)
    devices = jax.devices()
    log(f"[bench] hotswap: building {n_replicas} {model} replicas over "
        f"{len(devices)} device(s)")
    replicas = [EngineReplica(i, model=model,
                              device=devices[i % len(devices)],
                              buckets=buckets, precision=precision,
                              seed=seed, cost_prior=True,
                              max_queue_images=queue_images)
                for i in range(n_replicas)]
    for r in replicas:
        r.startup()
    pool = demo.request_pool(seed=seed + 123)
    init_fn, _ = get_model(model)
    # Fresh (independently initialised) weights per publish: a swap that
    # installed identical bytes would be unobservable.
    states = [init_train_state(init_fn, jax.random.PRNGKey(seed + 1 + k))
              for k in range(2 * publishes_per_row)]
    trace = demo.synthetic_load_trace(n_requests, offered_rps=offered_rps,
                                      seed=seed + 7,
                                      tiers=((0, 1, slo_ms),))
    span_s = trace[-1][0]
    drain_s = 2.0 + 3.0 * slo_ms / 1e3

    def _replay():
        router = ReplicaRouter(replicas)
        with router:
            client = LoopbackClient(router)
            stats = demo.replay_load(client, trace, pool=pool, seed=seed,
                                     drain_timeout_s=drain_s)
        return stats, router.stats()

    def _swap_row(rolling, row_states):
        exec_counts = [len(r.engine._exec) for r in replicas]
        samples = []
        scheds = [r.scheduler for r in replicas]

        with tempfile.TemporaryDirectory() as pub_dir:
            pub = WeightPublisher(pub_dir, fingerprint={"model": model})
            watcher = WeightWatcher(pub_dir, replicas, rolling=rolling,
                                    poll_interval_s=0.02)

            def _publish_mid():
                t_start = _time.time()
                for k, state in enumerate(row_states):
                    target = span_s * (k + 1) / (len(row_states) + 1.0)
                    dt = t_start + target - _time.time()
                    if dt > 0:
                        _time.sleep(dt)
                    samples.append({
                        "queued_images": sum(s.queue_depth()
                                             for s in scheds),
                        "outstanding_s": round(sum(s.outstanding_s()
                                                   for s in scheds), 6),
                    })
                    pub.publish(state)

            router = ReplicaRouter(replicas)
            with router:
                client = LoopbackClient(router)
                watcher.start()
                th = threading.Thread(target=_publish_mid, daemon=True)
                th.start()
                stats = demo.replay_load(client, trace, pool=pool,
                                         seed=seed, drain_timeout_s=drain_s)
                th.join()
                # Deterministic close: the last publish may land between
                # background polls — one awaited poll before stopping.
                watcher.poll_once(wait=True)
                watcher.stop()
            rstats = router.stats()
            rep = watcher.report()

        swap_ms = sorted(rep["swap_ms"])
        return {
            "rolling": rolling,
            "publishes": len(row_states),
            "installs": rep["installed"],
            "installed_version": rep["installed_version"],
            "weights_versions": [e["weights_version"]
                                 for e in rstats["replicas"]],
            "swap_ms_p50": round(float(np.percentile(swap_ms, 50)), 3),
            "swap_ms_p99": round(float(np.percentile(swap_ms, 99)), 3),
            "swap_ms_max": round(swap_ms[-1], 3),
            "swap_samples": len(swap_ms),
            "in_flight_at_publish": samples,
            "recompiles": sum(len(r.engine._exec) - c
                              for r, c in zip(replicas, exec_counts)),
            "goodput_rps": stats["goodput_rps"],
            "attainment": stats["attainment"],
            "replies": stats["replies"],
            "unresolved": stats["unresolved"],
        }

    out = {
        "backend": jax.default_backend(),
        "model": model,
        "replicas": n_replicas,
        "offered_rps": round(n_requests / max(span_s, 1e-9), 1),
        "slo_ms": slo_ms,
        "provenance": (
            "same single-physical-core host caveat as serving_load; the "
            "swap rows replay the steady row's exact trace while a "
            "background publisher lands fresh bundles at fixed fractions "
            "of the span, so the goodput dip is attributable to the swap "
            "machinery alone."),
    }

    log(f"[bench] hotswap: steady row, {n_requests} reqs @ "
        f"{out['offered_rps']} rps, SLO {slo_ms:g} ms")
    steady, _rs = _replay()
    out["steady"] = {"goodput_rps": steady["goodput_rps"],
                     "attainment": steady["attainment"],
                     "replies": steady["replies"],
                     "unresolved": steady["unresolved"]}

    for rolling in (True, False):
        name = "rolling" if rolling else "all_at_once"
        row_states = states[:publishes_per_row] if rolling \
            else states[publishes_per_row:]
        log(f"[bench] hotswap: {name} swap row, "
            f"{publishes_per_row} publishes mid-trace")
        row = _swap_row(rolling, row_states)
        row["goodput_dip_pct"] = round(
            100.0 * (1.0 - row["goodput_rps"]
                     / max(steady["goodput_rps"], 1e-9)), 2)
        out[name] = row
        log(f"[bench] hotswap: {name} swap_ms p50 {row['swap_ms_p50']} "
            f"p99 {row['swap_ms_p99']}, recompiles {row['recompiles']}, "
            f"goodput dip {row['goodput_dip_pct']}%")

    out["zero_recompiles"] = (out["rolling"]["recompiles"] == 0
                              and out["all_at_once"]["recompiles"] == 0)
    if not out["zero_recompiles"]:
        log("[bench] hotswap: WARNING executable caches GREW across a "
            "swap row — the weights-as-arguments contract is broken")
    return out


def run_elastic(log, *, headline_model: str = "vgg11", ndev=None,
                global_batch: int = 256, data_dir: str = "./data",
                max_iters: int = 50, microshards: int = 4) -> dict:
    """Elastic-layer numbers (``cs744_ddp_tpu/elastic/``), measured:

    * ``shrink`` — an injected mid-epoch ``rank_death`` at full world: the
      emergency-checkpoint + coordinator-shrink + rebuild-and-resume wall
      clock, the world transition, and the steps-lost accounting (strong
      scaling replays only the interrupted window — the step counter
      itself carries over unchanged).
    * ``grow`` — the shrunk run's checkpoint resumed back at the full
      world: resume-plan numbers plus the rebuild+catch-up wall clock.
    * ``degraded_throughput`` — steady-state throughput of the strong-
      scaling microshard window at world 1 (the ladder's synchronous
      fallback) vs the full mesh: what you KEEP while degraded.

    Standalone-callable, like ``run_robustness``."""
    import tempfile
    import time as _time

    import jax

    from cs744_ddp_tpu.elastic import ElasticCoordinator
    from cs744_ddp_tpu.ft import ChaosPlan, FTConfig
    from cs744_ddp_tpu.utils.metrics import WINDOW

    log = log or (lambda s: print(s, file=sys.stderr))
    ndev = ndev or len(jax.devices())
    # The pinned program exists at worlds dividing the microshard count.
    world = max(w for w in range(1, min(ndev, microshards) + 1)
                if microshards % w == 0 and global_batch % w == 0)
    lim = max(max_iters, 2 * WINDOW)
    out = {"protocol": "strong", "microshards": microshards,
           "world": world, "global_batch": global_batch}

    def mk(w, ft=None):
        return _make_trainer(headline_model, "allreduce", w,
                             global_batch=global_batch, data_dir=data_dir,
                             log=lambda s: None, limit_train_batches=lim,
                             limit_eval_batches=1, ft=ft, elastic="strong")

    if world < 2:
        log("[bench] elastic: single-device host — shrink/grow ladder "
            "needs world >= 2; measuring degraded throughput only")
    else:
        # Shrink: rank (world-1) dies mid-epoch; the coordinator walks the
        # ladder and the resumed run finishes the epoch at the new world.
        death_step = lim // 2
        log(f"[bench] elastic: shrink — rank_death at step {death_step} "
            f"of {lim}, world {world}")
        chaos = ChaosPlan([("rank_death", death_step, world - 1)])
        with tempfile.TemporaryDirectory() as ckpt:
            coord = ElasticCoordinator(
                lambda w: mk(w, ft=FTConfig(chaos=chaos)),
                world=world, global_batch=global_batch,
                microshards=microshards, chaos=chaos, log=lambda s: None)
            t0 = _time.time()
            tr = coord.run(1, ckpt)
            total_s = _time.time() - t0
            ev = next(e for e in coord.events if e["kind"] == "shrink")
            plan = tr.resume_plan
            out["shrink"] = {
                "from_world": ev["from_world"],
                "to_world": ev["to_world"],
                "death_step": ev["step"],
                # Coordinator decision latency (probe + plan + membership
                # transition) vs the full recovery including trainer
                # rebuild, re-staging and the resumed epoch remainder.
                "coordinator_recovery_s": round(ev["recovery_s"], 3),
                "total_run_s": round(total_s, 3),
                # Strong scaling: the step counter is world-invariant, so
                # the only re-executed work is the interrupted window.
                "steps_lost": (ev["step"] - plan.start_step
                               if plan is not None else 0),
            }

            # Grow: resume the shrunk run's checkpoint back at full world.
            log(f"[bench] elastic: grow — resuming at world {world}")
            t0 = _time.time()
            tr2 = mk(world)
            tr2.run(2, checkpoint_dir=ckpt)
            out["grow"] = {
                "to_world": world,
                "resume_run_s": round(_time.time() - t0, 3),
            }

    # Degraded-mode throughput: the pinned program at world 1 vs world N.
    def _ips(w):
        tr = mk(w)
        return max(tr.steady_state_throughput(
                       max_iters=max_iters, window_iters="epoch")[0]
                   for _ in range(2))

    log("[bench] elastic: degraded-mode throughput (world 1 fallback)")
    degraded = _ips(1)
    full = _ips(world) if world > 1 else degraded
    out["degraded_throughput"] = {
        "world1_images_per_sec": round(degraded, 2),
        f"world{world}_images_per_sec": round(full, 2),
        "degraded_fraction": round(degraded / full, 3) if full else None,
    }
    return out


# The compression cost sheet's tiers: the uncompressed controls first
# (per-param = the byte baseline the ISSUE ratios are against; ddp and
# overlap share its bytes and differ in schedule), then the lossy tiers.
COMPRESSION_TIERS = ("allreduce", "ddp", "overlap",
                     "compress-bf16", "compress-int8", "powersgd")


def run_compression(log, *, headline_model: str = "vgg11", ndev=None,
                    global_batch: int = 256, data_dir: str = "./data",
                    max_iters: int = 100,
                    tiers=COMPRESSION_TIERS) -> Optional[dict]:
    """Compression-tier cost sheet (round 7) on THIS host's mesh:

    * ``comm_result_mib`` — MEASURED collective result bytes from each
      tier's pre-optimization step lowering (the same accounting the
      audit's byte contracts certify — static, immune to host noise),
      with the ratio vs the uncompressed per-param tier,
    * ``wall_clock_s_best`` / ``images_per_sec_per_chip`` — interleaved
      min-over-rounds epoch wall clock: each round visits every tier
      once, so a host-contention burst inflates all tiers equally
      instead of landing on one entry (the test_spectrum_wallclock
      noise discipline), and
    * ``convergence_delta_pct`` — test accuracy after an IDENTICAL
      warm+timed training schedule per tier, minus the uncompressed
      ``allreduce`` tier's accuracy: the lossy tiers' accuracy cost,
      measured rather than promised.

    None (with a logged reason) on a single-device host — every tier's
    sync is a no-op there, so the sheet would be noise around zero."""
    import time as _time

    import jax

    from cs744_ddp_tpu.analysis import audit as auditlib

    log = log or (lambda s: print(s, file=sys.stderr))
    ndev = ndev or len(jax.devices())
    if ndev < 2:
        log("[bench] compression: single-device host — tiers collapse to "
            "no-op sync; section omitted")
        return None

    # Static comm bytes: one step-path lowering per tier (no compile).
    try:
        zoo = auditlib.audit_zoo(
            model=headline_model, global_batch=global_batch,
            strategies=tiers, paths=("step",), include_eval=False,
            num_devices=ndev)
    except Exception as e:   # noqa: BLE001 - advisory section
        log(f"[bench] compression: static lowering failed ({e!r}); "
            "section omitted")
        return None
    comm_mib = {
        r.program.rsplit("/", 1)[-1]:
            sum(r.stats.get("result_bytes", {}).values()) / 2**20
        for r in zoo.reports}

    lim = min(max_iters, 30)
    try:
        trainers = {}
        for t in tiers:
            log(f"[bench] compression: staging {headline_model}/{t} "
                f"on {ndev} device(s)")
            trainers[t] = _make_trainer(
                headline_model, t, ndev, global_batch=global_batch,
                data_dir=data_dir, log=lambda s: None,
                limit_train_batches=lim, limit_eval_batches=4)
        tr0 = trainers[tiers[0]]
        nfull, tail_per = tr0._per_rank_batch_counts()
        images = (min(lim, nfull) * global_batch
                  + (tail_per * tr0.world
                     if lim > nfull and tail_per else 0))
        for t in tiers:
            trainers[t].train_model(0)      # compile + warm
        best = {t: float("inf") for t in tiers}
        for _ in range(3):
            for t in tiers:
                t0 = _time.time()
                trainers[t].train_model(0)
                best[t] = min(best[t], _time.time() - t0)
        acc = {}
        for t in tiers:
            _, _, acc[t] = trainers[t].test_model()
    except Exception as e:   # noqa: BLE001 - advisory section
        log(f"[bench] compression: measurement failed ({e!r}); "
            "section omitted")
        return None

    base_mib = comm_mib.get("allreduce")
    out = {
        "protocol": f"{lim} batches/epoch, 1 warm + 3 interleaved timed "
                    f"epochs (min over rounds), global batch "
                    f"{global_batch}, f32",
        "world": ndev,
        "baseline_tier": "allreduce",
        "per_tier": {},
    }
    for t in tiers:
        out["per_tier"][t] = {
            "wall_clock_s_best": round(best[t], 3),
            "images_per_sec_per_chip": round(images / best[t] / ndev, 2),
            "comm_result_mib": round(comm_mib.get(t, 0.0), 4),
            "comm_ratio_vs_allreduce": (
                round(base_mib / comm_mib[t], 2)
                if base_mib and comm_mib.get(t) else None),
            "test_accuracy_pct": round(acc[t], 2),
            "convergence_delta_pct": round(acc[t] - acc["allreduce"], 2),
        }
    return out


def _zoo_result(log, *, headline_model: str, global_batch: int,
                collect_hlo: bool = False):
    """Lower + audit the shipped-program zoo once (shared by the audit
    and attribution sections — one set of lowerings feeds both); None
    with a logged reason on failure."""
    import jax

    from cs744_ddp_tpu.analysis import audit as auditlib

    ndev = len(jax.devices())
    log(f"[bench] audit: program zoo for {headline_model} on {ndev} "
        "device(s)")
    try:
        return auditlib.audit_zoo(model=headline_model,
                                  global_batch=global_batch,
                                  serve_buckets=(1, 8),
                                  num_devices=ndev,
                                  collect_hlo=collect_hlo)
    except Exception as e:   # noqa: BLE001 - advisory section
        log(f"[bench] audit: zoo audit failed ({e!r}); section omitted")
        return None


def run_audit(log, *, headline_model: str = "vgg11",
              global_batch: int = 256, zoo=None) -> Optional[dict]:
    """Static program audit (``cs744_ddp_tpu/analysis/audit.py``) over the
    full shipped-program zoo on THIS host's devices: every train path x
    strategy, the eval window and the serving ladder, certified against
    their per-strategy cost contracts (collective shapes + the depth
    ladder, dtype leaks, donation, host syncs, baked constants).  The
    bench artifact carries the certification next to the numbers it
    certifies.  None (with a logged reason) when auditing fails — the
    section is advisory, never fatal to a finished measurement run."""
    log = log or (lambda s: print(s, file=sys.stderr))
    res = zoo if zoo is not None else _zoo_result(
        log, headline_model=headline_model, global_batch=global_batch)
    if res is None:
        return None
    for line in res.format_lines():
        log(f"[bench] {line}")
    return res.summary()


def run_attribution(log, *, headline_model: str = "vgg11",
                    headline_strategy: str = "ddp", ndev=None,
                    global_batch: int = 256, data_dir: str = "./data",
                    max_iters: int = 100, zoo=None) -> Optional[dict]:
    """Performance attribution (round 8): the static cost model
    (``analysis/costmodel.py``) walked over every zoo program's lowering
    — analytic FLOPs, HBM bytes, collective wire bytes -> per-program
    roofline bound, MFU ceiling and comm/compute ratio — plus a MEASURED
    join on the headline windowed program: per-dispatch wall clock from a
    real steady-state run against the same program's analytic flops,
    yielding achieved MFU on the numbers the audit section certifies.
    The ``overlap`` tier additionally reports its exposed-communication
    upper bound against ``ddp``'s chained plan.  None (logged reason)
    when any leg fails — advisory, never fatal."""
    import jax

    from cs744_ddp_tpu.analysis import audit as auditlib
    from cs744_ddp_tpu.analysis import costmodel
    from cs744_ddp_tpu.obs import attribution as attrlib

    log = log or (lambda s: print(s, file=sys.stderr))
    ndev = ndev or len(jax.devices())
    res = zoo
    if res is None or not res.hlo:
        res = _zoo_result(log, headline_model=headline_model,
                          global_batch=global_batch, collect_hlo=True)
    if res is None:
        return None
    try:
        out = auditlib.zoo_attribution(res)
    except Exception as e:   # noqa: BLE001 - advisory section
        log(f"[bench] attribution: static leg failed ({e!r}); "
            "section omitted")
        return None
    log(f"[bench] attribution: {len(out['programs'])} programs "
        "cost-modeled")

    # Measured join: steady-state per-step wall clock of the headline
    # windowed program vs the SAME lowering's analytic per-device flops.
    prog = f"train/window/{headline_strategy}"
    try:
        rep = costmodel.cost_report(res.hlo[prog], prog)
        trips = max(rep.trip_counts.values(), default=1)
        log(f"[bench] attribution: measured join on {prog} "
            f"({headline_model}, {ndev} device(s))")
        trainer = _make_trainer(headline_model, headline_strategy, ndev,
                                global_batch=global_batch,
                                data_dir=data_dir, log=lambda s: None)
        ips_per_chip = trainer.steady_state_throughput(
            max_iters=max_iters, window_iters="epoch")[1]
        step_s = global_batch / (ips_per_chip * ndev)
        out["measured"] = {
            "protocol": f"{headline_model}/{headline_strategy} on {ndev} "
                        f"device(s), global batch {global_batch}; "
                        "steady-state per-step wall clock vs the audited "
                        "window lowering's per-device analytic flops",
            "images_per_sec_per_chip": round(ips_per_chip, 2),
            **attrlib.attribute(rep, measured_s=step_s * trips),
        }
    except Exception as e:   # noqa: BLE001 - advisory section
        log(f"[bench] attribution: measured join failed ({e!r}); "
            "static leg kept")
        out.pop("measured", None)
    return out


def run_memory(log, *, headline_model: str = "vgg11",
               global_batch: int = 256, zoo=None,
               planner_worlds=(1, 2, 8),
               planner_window: int = 4) -> Optional[dict]:
    """Memory certification (round 20): the static liveness certifier
    (``analysis/memlife.py``) over every zoo lowering — peak HBM
    residency per program vs the single-sourced v5e capacity — plus a
    compiled differential on the headline train window (static peak must
    clear XLA's ``memory_analysis()`` temp+output floor and stay within
    the declared band), the process's live-array gauge as a runtime
    cross-check, and the K-epoch feasibility table
    (``analysis/megaplan.max_feasible_K``) at 16 GiB for the mega-program
    ROADMAP item.  None (logged reason) when certification fails —
    advisory, never fatal."""
    import jax

    from cs744_ddp_tpu.analysis import (audit as auditlib, costmodel,
                                        megaplan, memlife)

    log = log or (lambda s: print(s, file=sys.stderr))
    res = zoo
    if res is None or not res.hlo:
        res = _zoo_result(log, headline_model=headline_model,
                          global_batch=global_batch, collect_hlo=True)
    if res is None:
        return None
    try:
        reports = {name: memlife.mem_report(text, name)
                   for name, text in res.hlo.items()}
    except Exception as e:   # noqa: BLE001 - advisory section
        log(f"[bench] memory: liveness sweep failed ({e!r}); "
            "section omitted")
        return None
    budget = costmodel.V5E_HBM_CAPACITY_BYTES
    fattest = max(reports.values(), key=lambda r: r.peak_bytes)
    log(f"[bench] memory: {len(reports)} programs certified; fattest "
        f"{fattest.name} at {fattest.peak_bytes / 2**20:.1f} MiB of "
        f"{budget / 2**20:.0f} MiB")
    out = {
        "protocol": "static buffer-liveness peak per zoo lowering "
                    "(analysis/memlife.py) vs the single-sourced v5e HBM "
                    "capacity; compiled differential on the headline "
                    "window; K-epoch planner (analysis/megaplan.py)",
        "budget_mib": round(budget / 2**20, 1),
        "peak_mib_by_program": {
            name: round(r.peak_bytes / 2**20, 3)
            for name, r in sorted(reports.items())},
        "max_peak": {
            "program": fattest.name,
            "peak_mib": round(fattest.peak_bytes / 2**20, 3),
            "headroom_mib": round(
                (budget - fattest.peak_bytes) / 2**20, 3),
        },
    }

    # Compiled differential: the same window the attribution section
    # measures, compiled here so the artifact records the static bound
    # sitting on the right side of XLA's own accounting.
    try:
        ndev = len(jax.devices())
        lowered, name = megaplan.lower_window(
            headline_model, world=ndev, global_batch=global_batch,
            strategy="ddp" if ndev > 1 else "single")
        rep = memlife.mem_report(auditlib._hlo_text(lowered), name)
        ms = lowered.compile().memory_analysis()
        bad = memlife.check_against_compiled(rep, ms, windowed=True)
        floor = ((getattr(ms, "temp_size_in_bytes", 0) or 0)
                 + (getattr(ms, "output_size_in_bytes", 0) or 0))
        out["compiled_check"] = {
            "program": name,
            "static_peak_mib": round(rep.peak_bytes / 2**20, 3),
            "compiled_floor_mib": round(floor / 2**20, 3),
            "band": memlife.COMPILED_BAND,
            "clean": not bad,
            "findings": bad,
        }
        log(f"[bench] memory: compiled check on {name} "
            f"{'clean' if not bad else 'FAILED'} (static "
            f"{rep.peak_bytes / 2**20:.1f} MiB vs floor "
            f"{floor / 2**20:.1f} MiB)")
    except Exception as e:   # noqa: BLE001 - advisory section
        log(f"[bench] memory: compiled differential failed ({e!r}); "
            "static sweep kept")

    # Runtime cross-check: what this process actually holds live on
    # device right now (the per-run gauge lives in telemetry; tier-1
    # pins gauge <= certificate on a real windowed run).
    try:
        live = jax.live_arrays()
        out["runtime_live_mib"] = round(
            sum(int(getattr(a, "nbytes", 0) or 0) for a in live) / 2**20,
            2)
        out["runtime_live_arrays"] = len(live)
    except Exception:   # noqa: BLE001 - backend without the API
        pass

    # K-epoch mega-program feasibility (ROADMAP item 3 entry criterion).
    plans = {}
    for w in planner_worlds:
        try:
            plan = megaplan.plan_feasibility(
                headline_model, w, planner_window,
                global_batch=global_batch)
            plans[str(w)] = plan.to_dict()
            log(f"[bench] memory: planner {headline_model} world {w} "
                f"window {planner_window} -> max_k {plan.max_k} "
                f"(saves {plan.round_trips_saved} round-trips)")
        except Exception as e:   # noqa: BLE001 - advisory section
            log(f"[bench] memory: planner world {w} failed ({e!r})")
    if plans:
        out["planner"] = {"model": headline_model,
                          "window": planner_window,
                          "per_world": plans}
    return out


def run_bench(*, matrix: bool = True, sweep: bool = True,
              peak: bool = True, convergence: bool = True,
              convergence_epochs: int = 3,
              spectrum: bool = True, host_pipeline: bool = True,
              compression: bool = True,
              robustness: bool = True, serving: bool = True,
              serving_load: bool = True,
              pipeline: bool = True,
              hotswap: bool = True,
              tracing: bool = True,
              elastic: bool = True,
              audit: bool = True,
              attribution: bool = True,
              memory: bool = True,
              serving_kwargs=None,
              max_iters: int = 100,
              global_batch: int = 256,
              models=MODELS, strategies=STRATEGIES, deep_rows=DEEP_ROWS,
              spectrum_deep_rows=(("resnet34", "allreduce"),
                                  ("resnet34", "ddp")),
              headline_model: str = "vgg11",
              peak_batch_candidates=(1536, 2048),
              log=None) -> dict:
    import jax

    log = log or (lambda s: print(s, file=sys.stderr))
    data_dir = os.environ.get("CIFAR_DATA_DIR", "./data")
    ndev = len(jax.devices())

    # Headline: the flagship config on all chips (ddp when the mesh is
    # non-trivial; Part-1 'single' semantics on one chip), best of
    # HEADLINE_RUNS independent runs with median/min recorded — see module
    # docstring and BASELINE.md for the one-sided-noise rationale.
    headline_strategy = "ddp" if ndev > 1 else "single"
    log(f"[bench] headline: {headline_model}/{headline_strategy} "
        f"on {ndev} device(s), best of {HEADLINE_RUNS}")
    headline_runs = []
    headline_flops = None
    for _ in range(HEADLINE_RUNS):
        ips, fl = _throughput(headline_model, headline_strategy, ndev,
                              global_batch=global_batch, max_iters=max_iters,
                              data_dir=data_dir, log=lambda s: None,
                              want_flops=headline_flops is None, repeats=2,
                              flops_log=log)
        headline_runs.append(ips)
        headline_flops = headline_flops or fl
    headline = max(headline_runs)

    result = {
        "metric": f"cifar10_{headline_model}_images_per_sec_per_chip",
        "value": round(headline, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(headline / TORCH_CPU_BASELINE_IPS, 2),
        "num_devices": ndev,
        "headline_stats": {
            "runs": [round(r, 2) for r in headline_runs],
            "best": round(max(headline_runs), 2),
            "median": round(statistics.median(headline_runs), 2),
            "min": round(min(headline_runs), 2),
        },
        **_mfu_fields(headline, headline_flops),
    }

    # Convergence oracle — the reference's own correctness signal (test
    # accuracy after training, /root/reference/src/Part 1/main.py:74-76),
    # tracked per round so the artifact carries it, not just a test
    # assertion — and as a TRAJECTORY (per-epoch accuracy over
    # ``convergence_epochs``; a half-broken step can luck into one
    # above-chance epoch, not a rising multi-epoch trend — VERDICT r4
    # item 3).  On this egress-less bench host the dataset is the
    # deterministic synthetic fallback (real_data=false; class-templated
    # noisy images, recalibrated round 7 so the reference config learns
    # GRADUALLY — rising epoch over epoch, between the 10% chance floor
    # and the label-noise ceiling); real-CIFAR accuracy remains
    # unverifiable here (BASELINE.md).
    if convergence:
        log(f"[bench] convergence: {headline_model}/{headline_strategy}, "
            f"{convergence_epochs} epochs @ reference config")
        # In-memory telemetry recorder (no out_dir): the section's steady-
        # state step-time percentiles ride along in the bench artifact.
        from cs744_ddp_tpu.obs import Telemetry
        conv_tel = Telemetry()
        trainer = _make_trainer(headline_model, headline_strategy, ndev,
                                global_batch=global_batch, data_dir=data_dir,
                                log=lambda s: None, telemetry=conv_tel)
        per_epoch = []
        first_loss = None
        for ep in range(convergence_epochs):
            timers = trainer.train_model(ep)
            if first_loss is None:
                first_loss = timers.losses[0]
            avg_loss, _, acc = trainer.test_model()
            per_epoch.append({
                "train_loss_last": round(timers.losses[-1], 4),
                "test_avg_loss": round(avg_loss, 4),
                "test_accuracy_pct": round(acc, 2),
            })
        result["convergence"] = {
            "protocol": f"{convergence_epochs} epochs, reference config "
                        f"(global batch {global_batch}, SGD 0.1/0.9/1e-4, "
                        "f32)",
            "train_loss_first": round(first_loss, 4),
            "train_loss_last": per_epoch[-1]["train_loss_last"],
            "test_avg_loss": per_epoch[-1]["test_avg_loss"],
            "test_accuracy_pct": per_epoch[-1]["test_accuracy_pct"],
            "per_epoch": per_epoch,
            "real_data": trainer.real_data,
            "telemetry_summary": conv_tel.finalize(
                global_batch=global_batch),
        }
        # Companion entry at a stable lr: a faster-learning 1-epoch control
        # next to the reference-lr trajectory.  On the ROUND-7 recalibrated
        # synthetic task (data/cifar10.py knob comments) the reference
        # lr=0.1 no longer collapses the net — it climbs epoch over epoch
        # (tiny @ 12.8k imgs: 16% -> 32% -> 35%) — but it starts slow, so
        # the CI learning floor rides on this lr=0.01 entry, which clears
        # chance decisively within one epoch (tiny @ 12.8k imgs: 50%).
        # Round-5 history (single-template task: lr 0.1 froze VGG-11 at
        # 19.7%, lr 0.01 hit 100% in one epoch) is preserved in BASELINE.md.
        from cs744_ddp_tpu.ops import sgd as _sgd
        stable_cfg = _sgd.SGDConfig(lr=0.01)
        log(f"[bench] convergence: {headline_model}/{headline_strategy}, "
            f"1 epoch @ stable lr {stable_cfg.lr}")
        tr2 = _make_trainer(headline_model, headline_strategy, ndev,
                            global_batch=global_batch, data_dir=data_dir,
                            log=lambda s: None, sgd_cfg=stable_cfg)
        timers2 = tr2.train_model(0)
        avg_loss2, _, acc2 = tr2.test_model()
        result["convergence"]["stable_lr"] = {
            "protocol": f"1 epoch, SGD {stable_cfg.lr}/"
                        f"{stable_cfg.momentum}/"
                        f"{stable_cfg.weight_decay}, f32",
            "train_loss_last": round(timers2.losses[-1], 4),
            "test_avg_loss": round(avg_loss2, 4),
            "test_accuracy_pct": round(acc2, 2),
        }

    if spectrum:
        # Always the full 3-tier cross (STRATEGIES default): the section's
        # information IS the tier contrast, so it does not follow a pruned
        # matrix ``strategies``.
        spec = _collect_spectrum(log, headline_model, global_batch,
                                 deep_rows=spectrum_deep_rows)
        if spec is not None:
            result["spectrum"] = spec

    if matrix:
        result["matrix"] = {}
        # flops depend on (model, batch) only — strategies and precision
        # share (a bf16 matmul performs the same multiply-adds).
        model_flops = {headline_model: headline_flops}
        for model, strategy in _matrix_pairs(ndev, models, strategies,
                                             deep_rows):
            entry_key = f"{model}/{strategy}"
            if model == headline_model and strategy == headline_strategy:
                # Iteration-for-iteration identical to a headline run —
                # reuse one run instead of another measurement.
                ips = headline_runs[0]
            else:
                log(f"[bench] matrix: {entry_key} on {ndev} device(s)")
                ips, fl = _throughput(
                    model, strategy, ndev, global_batch=global_batch,
                    max_iters=max_iters, data_dir=data_dir,
                    log=lambda s: None,
                    want_flops=model not in model_flops, repeats=2,
                    flops_log=log)
                model_flops.setdefault(model, fl)
            result["matrix"][entry_key] = {
                "images_per_sec_per_chip": round(ips, 2),
                **_mfu_fields(ips, model_flops.get(model)),
            }
        # One deep row in bf16 mixed precision at the parity batch: the
        # parity matrix is f32-only and the peak entry changes batch AND
        # precision at once, so neither isolates what mixed precision buys
        # a DEEP model at the reference's config (VERDICT r5 satellite).
        if deep_rows:
            bmodel, bstrat = deep_rows[-1]
            entry_key = f"{bmodel}/{bstrat}/bf16"
            log(f"[bench] matrix: {entry_key} on {ndev} device(s)")
            ips, fl = _throughput(
                bmodel, bstrat, ndev, global_batch=global_batch,
                max_iters=max_iters, data_dir=data_dir, log=lambda s: None,
                precision="bf16", want_flops=bmodel not in model_flops,
                repeats=2, flops_log=log)
            model_flops.setdefault(bmodel, fl)
            result["matrix"][entry_key] = {
                "images_per_sec_per_chip": round(ips, 2),
                "precision": "bf16",
                **_mfu_fields(ips, model_flops.get(bmodel)),
            }

    # Peak throughput: the parity protocol pins global batch 256 / f32
    # (the reference's config), which underfills the MXU on one chip; this
    # reports the frontier with both constraints lifted (bf16 mixed
    # precision, large per-chip batch) — same measurement design.  The
    # frontier is a SEARCH over the two best measured batch candidates
    # (1536 then 2048 images/chip; the day-long sweep measured
    # 1536 > 2048 > 2560 > 3072 on v5e, within a couple % of each other),
    # reporting the winning config — which also shields the headline peak
    # from a single moment of host contention.
    if peak:
        best, best_ips = None, None
        for per_chip_batch in dict.fromkeys(peak_batch_candidates):
            peak_global = per_chip_batch * ndev
            log(f"[bench] peak: {headline_model}/bf16/batch{peak_global} "
                f"on {ndev} device(s)")
            ips, fl = _throughput(
                headline_model, headline_strategy, ndev,
                global_batch=peak_global, max_iters=max(max_iters // 3, 2),
                data_dir=data_dir, log=lambda s: None,
                precision="bf16", want_flops=True, repeats=2,
                flops_log=log)
            # Compare UNROUNDED ips (the stored value is rounded; a
            # near-tie within the rounding step could otherwise pick a
            # candidate inconsistent with the reported numbers).
            if best_ips is None or ips > best_ips:
                best_ips = ips
                best = {
                    "config": f"{headline_model}/bf16/"
                              f"global_batch={peak_global}",
                    "images_per_sec_per_chip": round(ips, 2),
                    **_mfu_fields(ips, fl),
                }
        result["peak"] = best

    # Host-pipeline throughput: the --host-augment mode (the reference's
    # DataLoader-worker model — C++ crop/flip on host, windowed uint8
    # staging since round 5).  Regression-tracked here because its wins
    # were previously hand-measured only (BASELINE.md: 1,235 serial ->
    # 1,756 prefetched -> 13,805 windowed img/s on the tunneled v5e
    # host); bounded by the host->device link, not the chip.
    if host_pipeline:
        log(f"[bench] host_pipeline: {headline_model}/{headline_strategy}/"
            "--host-augment, chunked windowed")
        # Cap at 98 batches (~half an epoch at batch 256): the path is
        # host->device-link-bound at ~15 ms/batch on the tunneled host
        # (BASELINE.md), so a full --max-iters run would spend minutes
        # measuring the wire for no extra information.
        lim = min(max_iters, 98)
        if lim < max_iters:
            log(f"[bench] host_pipeline: capped at {lim} batches "
                f"(link-bound path; --max-iters {max_iters} applies to "
                "the device-bound sections)")
        from cs744_ddp_tpu.obs import Telemetry as _Telemetry
        host_tel = _Telemetry()   # in-memory; summary attached below
        trh = _make_trainer(headline_model, headline_strategy, ndev,
                            global_batch=global_batch, data_dir=data_dir,
                            log=lambda s: None, host_augment=True,
                            limit_train_batches=lim, telemetry=host_tel)
        # Images actually trained per epoch: the limit may exceed the
        # epoch's full-batch count (large global batches), in which case
        # the ragged tail trains too — assuming lim batches would inflate
        # the rate.
        nfull, tail_per = trh._per_rank_batch_counts()
        images = (min(lim, nfull) * global_batch
                  + (tail_per * trh.world
                     if lim > nfull and tail_per else 0))
        import time as _time
        trh.train_model(0)  # compile + warm
        best_ips = 0.0
        for _ in range(3):
            t0 = _time.time()
            trh.train_model(0)
            best_ips = max(best_ips, images / (_time.time() - t0))
        # Chunk-count sweep: K=1 is round 5's whole-window staging (the
        # degenerate control — no overlap), larger K trades per-put
        # fixed cost for compute/transfer overlap.  1 warm epoch +
        # best-of-2 per point (vs best-of-3 for the main K above).
        chunk_sweep = {str(trh.host_chunks): round(best_ips / ndev, 2)}
        for k in (1, 2, 8):
            if k == trh.host_chunks:
                continue
            log(f"[bench] host_pipeline: chunk_sweep K={k}")
            trk = _make_trainer(headline_model, headline_strategy, ndev,
                                global_batch=global_batch,
                                data_dir=data_dir, log=lambda s: None,
                                host_augment=True, host_chunks=k,
                                limit_train_batches=lim)
            trk.train_model(0)
            k_ips = 0.0
            for _ in range(2):
                t0 = _time.time()
                trk.train_model(0)
                k_ips = max(k_ips, images / (_time.time() - t0))
            chunk_sweep[str(k)] = round(k_ips / ndev, 2)
        from cs744_ddp_tpu.data import native as _native
        result["host_pipeline"] = {
            "mode": "chunked uint8 staging (fl_gather_augment_u8 into a "
                    "reusable arena, per-chunk device_put overlapped with "
                    "the previous window's compute, on-device "
                    "concatenate), normalize fused on device",
            # False = the C++ library failed to load and the NumPy
            # fallback ran — a much slower number that must not be read
            # as a regression of the native path.
            "native_lib": _native.available(),
            "host_chunks": trh.host_chunks,
            "images_per_sec_per_chip": round(best_ips / ndev, 2),
            # The pure-device_put ceiling this achieved number is judged
            # against (BASELINE.md VERDICT item 3 closure).
            "link_floor": measure_link_floor(
                log, global_batch=global_batch, ndev=ndev),
            "chunk_sweep": chunk_sweep,
            # Spans cover host_augment / chunk_put / chunk_wait wall
            # clock; percentiles cover the timed epochs' steady windows.
            "telemetry_summary": host_tel.finalize(
                global_batch=global_batch),
        }

    # Compression-tier cost sheet: measured comm bytes, interleaved
    # wall clock and the convergence delta vs the uncompressed tier
    # (round 7; the static byte CONTRACTS are certified by the audit
    # section — this is the measured companion).
    if compression:
        comp = run_compression(
            log, headline_model=headline_model, ndev=ndev,
            global_batch=global_batch, data_dir=data_dir,
            max_iters=max_iters)
        if comp is not None:
            result["compression"] = comp

    # Fault-tolerance cost/benefit: guard overhead, degraded-staging
    # fraction, emergency checkpoint wall clock, skip-policy demo.
    if robustness:
        result["robustness"] = run_robustness(
            log, headline_model=headline_model,
            headline_strategy=headline_strategy, ndev=ndev,
            global_batch=global_batch, data_dir=data_dir,
            max_iters=max_iters)

    # Serving fast path: ladder throughput curve, open-loop latency,
    # cold/warm startup (cs744_ddp_tpu/serve/).
    if serving:
        result["serving"] = run_serving(log, model=headline_model,
                                        **(serving_kwargs or {}))

    # Serving tier under load (round 9): replica scaling at fixed SLO,
    # goodput-vs-offered saturation, 2x tiered overload with confined
    # shedding, continuous-vs-drain queue-wait (cs744_ddp_tpu/serve/).
    if serving_load:
        result["serving_load"] = run_serving_load(log)

    # Dispatch pipeline (round 14): serial vs pipelined vs device-program
    # floor per rung, capacity A/B with the scheduler pipeline on/off,
    # stage waterfall + occupancy at the pipelined capacity point
    # (cs744_ddp_tpu/serve/ two-slot dispatch).
    if pipeline:
        result["pipeline"] = run_pipeline(log)

    # Train-to-serve weight hot-swap (round 10): swap latency p50/p99,
    # in-flight work at each publish instant, goodput dip vs the steady
    # row, rolling vs all-at-once — zero recompiles pinned
    # (cs744_ddp_tpu/publish/).
    if hotswap:
        result["hotswap"] = run_hotswap(log)

    # Distributed tracing (round 12): capacity with tracing off vs on
    # (<= 5% overhead pin), and a real two-OS-process run reconstructed
    # into skew-corrected waterfalls by obs/aggregate.py.
    if tracing:
        result["tracing"] = run_tracing(log)

    # Elastic layer: shrink/grow resume latency, steps lost, and
    # degraded single-rank throughput (cs744_ddp_tpu/elastic/).
    if elastic:
        result["elastic"] = run_elastic(
            log, headline_model=headline_model, ndev=ndev,
            global_batch=global_batch, data_dir=data_dir,
            max_iters=max_iters)

    # Static program audit + cost-model attribution + memory
    # certification: ONE set of zoo lowerings feeds all three sections —
    # the certification and the numbers cannot drift apart.
    if audit or attribution or memory:
        zoo = _zoo_result(log, headline_model=headline_model,
                          global_batch=global_batch,
                          collect_hlo=attribution or memory)
        if audit:
            audit_summary = run_audit(log, headline_model=headline_model,
                                      global_batch=global_batch, zoo=zoo)
            if audit_summary is not None:
                result["audit"] = audit_summary
        if attribution:
            attr = run_attribution(
                log, headline_model=headline_model,
                headline_strategy=headline_strategy, ndev=ndev,
                global_batch=global_batch, data_dir=data_dir,
                max_iters=max_iters, zoo=zoo)
            if attr is not None:
                result["attribution"] = attr
        if memory:
            mem = run_memory(log, headline_model=headline_model,
                             global_batch=global_batch, zoo=zoo)
            if mem is not None:
                result["memory"] = mem

    if sweep:
        # WEAK scaling: per-chip batch held at ``global_batch`` while the
        # mesh grows (global = global_batch x n).  The north star is
        # images/sec/CHIP efficiency (BASELINE.json >=90% at 1->8), which
        # is a constant-per-chip-work metric: at the reference's fixed
        # global 256 on 8 chips the per-chip batch would be 32 against a
        # full 37 MB gradient all-reduce per step — comm-dominated by
        # construction, measuring the protocol rather than the framework.
        # The reference's own strong-scaling config (global 256 divided
        # across workers) is what the MATRIX measures.
        counts = [n for n in (1, 2, 4, 8, 16, 32, 64) if n <= ndev]
        if counts[-1] != ndev:
            counts.append(ndev)
        per_chip, sweep_flops = {}, {}
        for n in counts:
            strat_n = "ddp" if n > 1 else "single"
            # n=1 with per-chip batch == global_batch is exactly a headline
            # run's config on a 1-chip host: reuse one run's value (same
            # best-of-2-per-trainer statistic as fresh sweep points).
            if n == 1 and ndev == 1 and strat_n == headline_strategy:
                per_chip[n] = headline_runs[0]
                sweep_flops[n] = headline_flops
                continue
            log(f"[bench] sweep: {headline_model}/{strat_n} on {n} "
                f"device(s), global batch {global_batch * n}")
            per_chip[n], sweep_flops[n] = _throughput(
                headline_model, strat_n, n, global_batch=global_batch * n,
                max_iters=max_iters, data_dir=data_dir, log=lambda s: None,
                repeats=2, want_flops=True, flops_log=log)
        base = per_chip[1]
        result["scaling"] = {
            "protocol": f"weak scaling, {global_batch} images/chip",
            "images_per_sec_per_chip": {str(n): round(v, 2)
                                        for n, v in per_chip.items()},
            "efficiency_vs_1chip": {str(n): round(v / base, 3)
                                    for n, v in per_chip.items()},
            "mfu_vs_bf16_peak": {
                str(n): _mfu_fields(v, sweep_flops[n]).get("mfu_vs_bf16_peak")
                for n, v in per_chip.items()},
        }

        # STRONG scaling — the reference's own protocol (global batch 256
        # DIVIDED across workers, Part 2a/main.py:22): the per-chip batch
        # shrinks as the mesh grows, so comm exposure rises by construction
        # (BASELINE.md "Scaling protocol").  Reported alongside the weak
        # sweep so both protocols are on the record; efficiency is
        # global-throughput(n) / (n x global-throughput(1)), which reduces
        # to the same per-chip ratio as the weak formula.
        strong_counts = [n for n in counts if global_batch % n == 0]
        strong = {}
        for n in strong_counts:
            strat_n = "ddp" if n > 1 else "single"
            if n == 1 and 1 in per_chip:
                strong[n] = per_chip[1]   # identical config: reuse
                continue
            log(f"[bench] sweep(strong): {headline_model}/{strat_n} on {n} "
                f"device(s), global batch {global_batch}")
            strong[n], _ = _throughput(
                headline_model, strat_n, n, global_batch=global_batch,
                max_iters=max_iters, data_dir=data_dir, log=lambda s: None,
                repeats=2)
        result["scaling"]["strong"] = {
            "protocol": f"strong scaling, global batch {global_batch} "
                        "(the reference's config)",
            "images_per_sec": {str(n): round(v * n, 2)
                               for n, v in strong.items()},
            "efficiency_vs_1chip": {str(n): round(v / strong[1], 3)
                                    for n, v in strong.items()},
        }
    return result


# The compact head's keys (module docstring "Emission contract"): the
# driver tail-captures ~2000 bytes of stdout and JSON-parses the LAST
# line, so the head carries only the fixed-size summary fields plus a
# pointer to the sidecar with everything else.
CONTRACT_KEYS = ("metric", "value", "unit", "vs_baseline", "num_devices",
                 "headline_stats", "tflops_per_sec", "mfu_vs_bf16_peak")
HEAD_LINE_BUDGET = 1800   # bytes, < the driver's ~2000-byte tail capture


def emit_result(result: dict, sidecar_path: str, out=print) -> dict:
    """Emit a bench result per the driver contract: full payload FIRST (one
    stdout line + the ``sidecar_path`` file), compact head as the FINAL
    stdout line.  Rounds 4/5 printed the full payload as the last line and
    overflowed the driver's tail capture ("parsed": null in BENCH_r04/r05)
    — hence the split, and the hard size check on the head.  Returns the
    head dict; tests/test_bench.py pins both emissions."""
    payload = json.dumps(result)
    # Self-validate before emitting: a non-serializable value (numpy
    # scalar, NaN under a strict parser) must fail HERE with a clear
    # error, not downstream in the consumer.
    reparsed = json.loads(payload)
    if reparsed.keys() != result.keys():
        raise RuntimeError("bench JSON round-trip dropped keys: "
                           f"{set(result) ^ set(reparsed)}")
    # Atomic sidecar publish: a bench killed (or preempted) mid-write must
    # leave the previous BENCH_FULL.json intact, never a torn one — the
    # committed artifact is read by drivers and tests.
    tmp = f"{sidecar_path}.{os.getpid()}.tmp"
    try:
        with open(tmp, "w") as f:
            f.write(payload + "\n")
        os.replace(tmp, sidecar_path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    out(payload)
    head = {k: result[k] for k in CONTRACT_KEYS if k in result}
    head["full_payload_file"] = os.path.basename(sidecar_path)
    head_line = json.dumps(head)
    if len(head_line) > HEAD_LINE_BUDGET:
        raise RuntimeError(
            f"bench head line is {len(head_line)} bytes, over the "
            f"{HEAD_LINE_BUDGET}-byte driver budget; trim CONTRACT_KEYS")
    out(head_line)
    return head


def _enable_compilation_cache() -> None:
    """Persist XLA compilations (the matrix compiles six train-window
    programs, ~40 s each on TPU, identical across bench invocations)."""
    from cs744_ddp_tpu.utils.compcache import \
        enable_persistent_compilation_cache
    enable_persistent_compilation_cache(
        os.path.dirname(os.path.abspath(__file__)))


def main(argv=None) -> None:
    p = argparse.ArgumentParser("bench")
    p.add_argument("--no-matrix", action="store_true",
                   help="headline metric only (fast driver mode; also "
                        "skips the peak, convergence and spectrum "
                        "sections)")
    p.add_argument("--no-sweep", action="store_true",
                   help="skip the 1..N-device scaling sweep")
    p.add_argument("--no-peak", action="store_true",
                   help="skip the bf16 large-batch peak-throughput entry")
    p.add_argument("--no-convergence", action="store_true",
                   help="skip the 1-epoch accuracy (convergence oracle) "
                        "entry")
    p.add_argument("--no-spectrum", action="store_true",
                   help="skip the static per-strategy collective-stats "
                        "section (v5e-8 AOT lowering)")
    p.add_argument("--no-host-pipeline", action="store_true",
                   help="skip the windowed --host-augment throughput entry")
    p.add_argument("--no-compression", action="store_true",
                   help="skip the compression-tier cost sheet (measured "
                        "comm bytes, interleaved wall clock, convergence "
                        "delta vs the uncompressed tier)")
    p.add_argument("--no-robustness", action="store_true",
                   help="skip the fault-tolerance cost/benefit section "
                        "(guard overhead, degraded staging, emergency "
                        "checkpoint timing, skip-policy demo)")
    p.add_argument("--no-serving", action="store_true",
                   help="skip the serving fast-path section (bucket "
                        "throughput curve, open-loop latency, cold/warm "
                        "startup)")
    p.add_argument("--no-serving-load", action="store_true",
                   help="skip the serving-tier load section (replica "
                        "scaling at fixed SLO, goodput-vs-offered curve, "
                        "2x tiered overload with confined shedding, "
                        "continuous-vs-drain queue-wait)")
    p.add_argument("--no-pipeline", action="store_true",
                   help="skip the dispatch-pipeline section (serial vs "
                        "pipelined vs device-program floor per rung, "
                        "capacity A/B with the scheduler pipeline on/off, "
                        "stage waterfall + two-slot occupancy)")
    p.add_argument("--no-hotswap", action="store_true",
                   help="skip the weight hot-swap section (swap latency "
                        "p50/p99, in-flight work at publish, goodput dip "
                        "vs steady, rolling vs all-at-once, zero-recompile "
                        "pin)")
    p.add_argument("--no-tracing", action="store_true",
                   help="skip the distributed-tracing section (capacity "
                        "tracing off vs on with the 5% overhead pin, "
                        "two-OS-process waterfall reconstruction, "
                        "aggregation wall clock)")
    p.add_argument("--no-elastic", action="store_true",
                   help="skip the elastic section (shrink/grow resume "
                        "latency, steps lost, degraded single-rank "
                        "throughput)")
    p.add_argument("--no-audit", action="store_true",
                   help="skip the static program-zoo audit section "
                        "(analysis/audit.py cost-shape certification)")
    p.add_argument("--no-attribution", action="store_true",
                   help="skip the cost-model attribution section "
                        "(analysis/costmodel.py analytic FLOPs/bytes per "
                        "zoo program + the measured MFU join on the "
                        "headline windowed program)")
    p.add_argument("--no-memory", action="store_true",
                   help="skip the memory certification section "
                        "(analysis/memlife.py peak-HBM liveness per zoo "
                        "program, the compiled differential, and the "
                        "analysis/megaplan.py K-epoch feasibility table)")
    p.add_argument("--max-iters", type=int, default=100,
                   help="minimum steady-state iterations per config")
    p.add_argument("--global-batch", type=int, default=256)
    p.add_argument("--require-real-data", action="store_true",
                   help="fail before measuring anything if CIFAR_DATA_DIR "
                        "(default ./data) holds no real CIFAR-10 pickle "
                        "batches — the right mode for any bench whose "
                        "convergence numbers will be read as CIFAR-10 "
                        "results (throughput is data-independent)")
    p.add_argument("--full-out", default=None,
                   help="path for the full-payload JSON sidecar (default: "
                        "BENCH_FULL.json next to this script; the compact "
                        "final-stdout-line head names it in "
                        "full_payload_file)")
    args = p.parse_args(argv)

    if args.require_real_data:
        from cs744_ddp_tpu.data import cifar10
        data_dir = os.environ.get("CIFAR_DATA_DIR", "./data")
        if not cifar10.has_real_data(data_dir):
            raise SystemExit(
                f"--require-real-data: no CIFAR-10 pickle batches under "
                f"{data_dir!r} (expected "
                f"{data_dir}/cifar-10-batches-py/data_batch_*); refusing "
                "to bench against the synthetic stand-in")

    _enable_compilation_cache()
    result = run_bench(matrix=not args.no_matrix, sweep=not args.no_sweep,
                       peak=not (args.no_peak or args.no_matrix),
                       convergence=not (args.no_convergence
                                        or args.no_matrix),
                       spectrum=not (args.no_spectrum or args.no_matrix),
                       host_pipeline=not (args.no_host_pipeline
                                          or args.no_matrix),
                       compression=not (args.no_compression
                                        or args.no_matrix),
                       robustness=not (args.no_robustness
                                       or args.no_matrix),
                       serving=not (args.no_serving or args.no_matrix),
                       serving_load=not (args.no_serving_load
                                         or args.no_matrix),
                       pipeline=not (args.no_pipeline or args.no_matrix),
                       hotswap=not (args.no_hotswap or args.no_matrix),
                       tracing=not (args.no_tracing or args.no_matrix),
                       elastic=not (args.no_elastic or args.no_matrix),
                       audit=not (args.no_audit or args.no_matrix),
                       attribution=not (args.no_attribution
                                        or args.no_matrix),
                       memory=not (args.no_memory or args.no_matrix),
                       max_iters=args.max_iters,
                       global_batch=args.global_batch)
    emit_result(result, args.full_out or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_FULL.json"))


if __name__ == "__main__":
    main()
